"""Battery for the multi-tenant solve service (pydcop_tpu/serving):
binning correctness (two structures never share a dispatch;
same-structure requests coalesce), batch results bit-identical to
solo engine runs, backpressure 429s at the high-water mark, breaker
opening on repeated dispatch failure (and /healthz reflecting it),
the bin-padding accounting in engine/batch, the /healthz
accelerator-probe surfacing, and a concurrent-client soak with no
lost or duplicated responses."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine import batch as engine_batch
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.engine.runner import MaxSumEngine
from pydcop_tpu.serving import binning
from pydcop_tpu.serving.admission import (
    AdmissionController,
    AdmissionPolicy,
    QueueFull,
    ServiceUnavailable,
)
from pydcop_tpu.serving.service import SolveService

MAX_CYCLES = 40
PARAMS = {"max_cycles": MAX_CYCLES}


def _instance(n: int, seed: int, chords: bool = False) -> DCOP:
    """Ring (optionally chorded) coloring with random cost tables:
    same (n, chords) -> same structure bin; seed varies the tables."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"s{n}_{seed}_{chords}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n) for i in range(n)]
    if chords:
        edges += [(i, (i + n // 2) % n) for i in range(0, n, 3)]
    for k, (i, j) in enumerate(edges):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _service(**kw) -> SolveService:
    kw.setdefault("batch_window_s", 0.1)
    kw.setdefault("max_batch", 8)
    return SolveService(**kw)


# ------------------------------------------------------------------ #
# binning


class TestBinning:
    def test_same_structure_same_key(self):
        g1, _ = compile_dcop(_instance(10, 0), noise_level=0.01)
        g2, _ = compile_dcop(_instance(10, 7), noise_level=0.01)
        params = binning.normalize_params(PARAMS)
        assert binning.bin_key(g1, params) == binning.bin_key(
            g2, params)

    def test_different_topology_different_key(self):
        """Same variable count and shapes can still be different
        structures (chords move scope indices): keys must differ."""
        g1, _ = compile_dcop(_instance(12, 0), noise_level=0.01)
        g2, _ = compile_dcop(
            _instance(12, 0, chords=True), noise_level=0.01)
        params = binning.normalize_params(PARAMS)
        assert binning.bin_key(g1, params) != binning.bin_key(
            g2, params)

    def test_different_params_different_key(self):
        g, _ = compile_dcop(_instance(10, 0), noise_level=0.01)
        p1 = binning.normalize_params({"max_cycles": 40})
        p2 = binning.normalize_params({"max_cycles": 50})
        assert binning.bin_key(g, p1) != binning.bin_key(g, p2)

    def test_unknown_param_rejected(self):
        with pytest.raises(ValueError, match="unknown solver param"):
            binning.normalize_params({"cycles": 10})

    def test_bin_label_is_short(self):
        g, _ = compile_dcop(_instance(10, 0), noise_level=0.01)
        key = binning.bin_key(g, binning.normalize_params(PARAMS))
        assert len(binning.bin_label(key)) < 40


# ------------------------------------------------------------------ #
# bin padding (engine/batch)


class TestBinPadding:
    def test_bin_size_ladder(self):
        assert engine_batch.bin_size_for(3, (1, 2, 4, 8)) == 4
        assert engine_batch.bin_size_for(4, (1, 2, 4, 8)) == 4
        assert engine_batch.bin_size_for(9, (1, 2, 4, 8)) == 9

    def test_pad_fraction_reported_in_metrics(self):
        graphs = [compile_dcop(_instance(8, s), noise_level=0.01)[0]
                  for s in range(3)]
        _, _, batch_result = engine_batch.run_stacked(
            graphs, max_cycles=10, pad_to_bins=(1, 2, 4, 8))
        metrics = batch_result.metrics
        assert metrics["batch_size"] == 4
        assert metrics["n_real"] == 3
        assert metrics["pad_fraction"] == pytest.approx(0.25)

    def test_no_padding_zero_fraction(self):
        graphs = [compile_dcop(_instance(8, s), noise_level=0.01)[0]
                  for s in range(4)]
        _, _, batch_result = engine_batch.run_stacked(
            graphs, max_cycles=10, pad_to_bins=(1, 2, 4, 8))
        assert batch_result.metrics["pad_fraction"] == 0.0
        assert batch_result.metrics["batch_size"] == 4

    def test_padded_results_match_unpadded(self):
        """Padding lanes must not leak into real lanes: values for
        the first n_real instances are identical with and without
        padding."""
        graphs = [compile_dcop(_instance(8, s), noise_level=0.01)[0]
                  for s in range(3)]
        v_pad, c_pad, _ = engine_batch.run_stacked(
            graphs, max_cycles=10, pad_to_bins=(1, 2, 4, 8))
        v_raw, c_raw, _ = engine_batch.run_stacked(
            graphs, max_cycles=10)
        assert np.array_equal(v_pad, v_raw)
        assert np.array_equal(c_pad, c_raw)

    def test_solve_maxsum_batch_carries_batch_metrics(self):
        dcops = [_instance(8, s) for s in range(3)]
        results = engine_batch.solve_maxsum_batch(
            dcops, max_cycles=10, pad_to_bins=(1, 2, 4, 8))
        assert all(r["batch"]["pad_fraction"] == pytest.approx(0.25)
                   for r in results)


# ------------------------------------------------------------------ #
# admission


class TestAdmission:
    def test_high_water_rejects(self):
        ctl = AdmissionController(AdmissionPolicy(high_water=3))
        ctl.admit(2)
        with pytest.raises(QueueFull):
            ctl.admit(3)

    def test_breaker_opens_after_failures_and_recovers(self):
        # Long reset for the rejection phase: the breaker-open flight
        # bundle dump (process-global, always-on) can take > 50 ms in
        # a full suite run, and a tiny reset window would already be
        # HALF-OPEN by the time admit() runs (observed flake).
        ctl = AdmissionController(AdmissionPolicy(
            high_water=10, breaker_failures=2, breaker_reset_s=30.0))
        ctl.admit(0)
        ctl.record_dispatch(ok=False)
        ctl.admit(0)  # one failure: still closed
        ctl.record_dispatch(ok=False)
        assert ctl.breaker_state == "open"
        with pytest.raises(ServiceUnavailable):
            ctl.admit(0)
        # Recovery phase on its own controller with a short reset
        # (its bundle is rate-limited away by the first trip above).
        ctl = AdmissionController(AdmissionPolicy(
            high_water=10, breaker_failures=2, breaker_reset_s=0.05))
        ctl.record_dispatch(ok=False)
        ctl.record_dispatch(ok=False)
        time.sleep(0.06)
        # Half-open admits; a successful probe dispatch closes it.
        ctl.admit(0)
        ctl.record_dispatch(ok=True)
        assert ctl.breaker_state == "closed"


# ------------------------------------------------------------------ #
# service dispatch semantics


class TestServiceDispatch:
    def test_same_structure_requests_coalesce(self):
        with _service(batch_window_s=0.3) as svc:
            ids = [svc.submit(_instance(10, s), params=PARAMS)
                   for s in range(5)]
            results = [svc.result(i, wait=60) for i in ids]
        assert all(r["status"] == "FINISHED" for r in results)
        assert svc.dispatches < 5
        assert svc.batched_dispatches >= 1
        # Shared-dispatch evidence on the results themselves.
        assert any(r["batch"]["n_real"] > 1 for r in results)

    def test_two_structures_never_share_a_dispatch(self):
        seen_bins = []
        with _service(batch_window_s=0.3) as svc:
            real_dispatch = svc.dispatch

            def spy(reqs):
                seen_bins.append({r.bin for r in reqs})
                real_dispatch(reqs)

            svc.dispatch = spy
            ids = [svc.submit(_instance(10, s), params=PARAMS)
                   for s in range(3)]
            ids += [svc.submit(_instance(14, s), params=PARAMS)
                    for s in range(3)]
            results = [svc.result(i, wait=60) for i in ids]
        assert all(r["status"] == "FINISHED" for r in results)
        assert len(seen_bins) >= 2
        # Every dispatch was bin-pure.
        assert all(len(bins) == 1 for bins in seen_bins)

    def test_results_bit_identical_to_solo_solves(self):
        dcops = [_instance(12, s) for s in range(4)]
        with _service(batch_window_s=0.3) as svc:
            ids = [svc.submit(d, params=PARAMS) for d in dcops]
            results = [svc.result(i, wait=60) for i in ids]
        for dcop, res in zip(dcops, results):
            graph, meta = compile_dcop(dcop, noise_level=0.01)
            solo = MaxSumEngine(graph, meta).run(
                max_cycles=MAX_CYCLES, stop_on_convergence=False)
            assert res["assignment"] == solo.assignment
            assert res["cost"] == dcop.solution_cost(
                res["assignment"])[0]

    def test_latency_accounting_present(self):
        with _service() as svc:
            rid = svc.submit(_instance(10, 0), params=PARAMS)
            res = svc.result(rid, wait=60)
        lat = res["latency"]
        assert lat["total_s"] > 0
        assert lat["dispatch_s"] > 0
        assert lat["total_s"] >= lat["dispatch_s"]

    def test_unknown_request_id_raises(self):
        with _service() as svc:
            with pytest.raises(KeyError):
                svc.result("nope")

    def test_submit_rejects_unknown_param(self):
        with _service() as svc:
            with pytest.raises(ValueError, match="unknown solver"):
                svc.submit(_instance(8, 0), params={"bogus": 1})

    def test_unhashable_param_rejected_and_service_survives(self):
        """An unhashable param value must fail the SUBMIT (400), not
        reach the scheduler's bin map and kill its thread — after the
        rejection the service still serves."""
        from pydcop_tpu.observability.metrics import (
            registry as reg,
        )

        with _service() as svc:
            before = reg.value("pydcop_requests_total",
                               status="rejected_bad_request")
            with pytest.raises(ValueError, match="bad solver param"):
                svc.submit(_instance(8, 0),
                           params={"damping": [0.5]})
            with pytest.raises(ValueError, match="damping_nodes"):
                svc.submit(_instance(8, 0),
                           params={"damping_nodes": "everything"})
            # Bad submits are ledger entries too.
            assert reg.value(
                "pydcop_requests_total",
                status="rejected_bad_request") == before + 2
            rid = svc.submit(_instance(8, 1), params=PARAMS)
            assert svc.result(rid, wait=60)["status"] == "FINISHED"

    def test_decode_failure_fails_request_not_scheduler(self):
        """A result decode that raises (bad meta) errors that one
        request; batch-mates and later requests still complete."""
        with _service(batch_window_s=0.3) as svc:
            poisoned = _instance(10, 0)
            healthy = [_instance(10, s) for s in (1, 2)]
            ids = {}
            ids[poisoned.name] = svc.submit(poisoned, params=PARAMS)
            for d in healthy:
                ids[d.name] = svc.submit(d, params=PARAMS)
            # Poison AFTER submit: break the stored request's meta so
            # only the decode (scheduler-side) fails.
            with svc._lock:
                req = svc._requests[ids[poisoned.name]]
            req.meta = None
            bad = svc.result(ids[poisoned.name], wait=60)
            assert bad["status"] == "ERROR"
            assert "decode failed" in bad["error"]
            for d in healthy:
                res = svc.result(ids[d.name], wait=60)
                assert res["status"] == "FINISHED"
            # Scheduler alive: a fresh request still serves.
            rid = svc.submit(_instance(10, 9), params=PARAMS)
            assert svc.result(rid, wait=60)["status"] == "FINISHED"

    def test_result_retention_prunes_completed(self):
        with _service(result_keep=3) as svc:
            ids = [svc.submit(_instance(8, s), params=PARAMS)
                   for s in range(3)]
            for i in ids:
                assert svc.result(i, wait=60) is not None
            # A 4th submit evicts the oldest completed result.
            last = svc.submit(_instance(8, 9), params=PARAMS)
            assert svc.result(last, wait=60) is not None
            with pytest.raises(KeyError):
                svc.result(ids[0])


# ------------------------------------------------------------------ #
# backpressure + breaker through the service


class TestBackpressure:
    def test_429_at_high_water_no_lost_requests(self):
        gate = threading.Event()
        svc = _service(
            max_queue=16, batch_window_s=0.01, max_batch=2,
            admission=AdmissionPolicy(high_water=3))
        real_run = svc._run_batch

        def slowed(reqs, params):
            gate.wait(30)
            return real_run(reqs, params)

        svc._run_batch = slowed
        svc.start()
        try:
            accepted, rejected = [], 0
            for s in range(10):
                try:
                    accepted.append(
                        svc.submit(_instance(8, s), params=PARAMS))
                except QueueFull:
                    rejected += 1
            assert rejected >= 1
            gate.set()
            results = [svc.result(i, wait=60) for i in accepted]
            assert all(r is not None and r["status"] == "FINISHED"
                       for r in results)
            # The ledger balances: every submit is accounted.
            assert svc.completed == len(accepted)
        finally:
            gate.set()
            svc.stop(drain=False)

    def test_breaker_opens_and_healthz_reflects_it(self):
        svc = _service(
            batch_window_s=0.01,
            admission=AdmissionPolicy(
                high_water=64, breaker_failures=2,
                breaker_reset_s=60.0))

        def failing(reqs, params):
            raise RuntimeError("engine down")

        svc._run_batch = failing
        svc.start()
        from pydcop_tpu.serving.http import ServeFrontEnd

        front = ServeFrontEnd(svc, port=0).start()
        try:
            for s in range(2):
                rid = svc.submit(_instance(8, s), params=PARAMS)
                res = svc.result(rid, wait=30)
                assert res["status"] == "ERROR"
                assert "dispatch failed" in res["error"]
            assert svc.admission.breaker_state == "open"
            with pytest.raises(ServiceUnavailable):
                svc.submit(_instance(8, 5), params=PARAMS)
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    front.url + "/healthz", timeout=10)
            assert err.value.code == 503
            body = json.loads(err.value.read())
            assert body["status"] == "failing"
            assert body["serving"]["breaker_state"] == "open"
        finally:
            front.stop()
            svc.stop(drain=False)

    def test_dispatch_failure_fails_batch_not_service(self):
        """One poisoned dispatch must not wedge the scheduler: later
        (recovered) dispatches still serve."""
        svc = _service(
            batch_window_s=0.01,
            admission=AdmissionPolicy(
                high_water=64, breaker_failures=5))
        real_run = svc._run_batch
        fail_once = [True]

        def flaky(reqs, params):
            if fail_once[0]:
                fail_once[0] = False
                raise RuntimeError("transient")
            return real_run(reqs, params)

        svc._run_batch = flaky
        svc.start()
        try:
            r1 = svc.submit(_instance(8, 0), params=PARAMS)
            assert svc.result(r1, wait=30)["status"] == "ERROR"
            r2 = svc.submit(_instance(8, 1), params=PARAMS)
            assert svc.result(r2, wait=60)["status"] == "FINISHED"
        finally:
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# HTTP front end


class TestHttpFrontEnd:
    def test_post_solve_wait_and_poll(self):
        from pydcop_tpu.dcop.yamldcop import dcop_yaml
        from pydcop_tpu.serving.http import ServeFrontEnd

        svc = _service(batch_window_s=0.05)
        svc.start()
        front = ServeFrontEnd(svc, port=0).start()
        try:
            yaml_src = dcop_yaml(_instance(10, 3))
            req = urllib.request.Request(
                front.url + "/solve",
                data=json.dumps({
                    "dcop": yaml_src, "wait": True,
                    "params": PARAMS}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
            assert body["status"] == "FINISHED"
            assert body["assignment"]

            # Async submit + poll.
            req = urllib.request.Request(
                front.url + "/solve",
                data=json.dumps({"dcop": yaml_src,
                                 "params": PARAMS}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert resp.status == 202
                rid = json.loads(resp.read())["id"]
            deadline = time.monotonic() + 30
            status = None
            while time.monotonic() < deadline:
                with urllib.request.urlopen(
                        front.url + f"/result/{rid}",
                        timeout=10) as resp:
                    if resp.status == 200:
                        status = json.loads(resp.read())["status"]
                        break
                time.sleep(0.05)
            assert status == "FINISHED"

            # /stats and /metrics mounted alongside.
            with urllib.request.urlopen(front.url + "/stats",
                                        timeout=10) as resp:
                stats = json.loads(resp.read())
            assert stats["completed"] >= 2
            with urllib.request.urlopen(front.url + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            assert "pydcop_requests_total" in text
            assert "pydcop_request_latency_seconds" in text
        finally:
            front.stop()
            svc.stop(drain=False)

    def test_bad_bodies_400_unknown_404(self):
        from pydcop_tpu.serving.http import ServeFrontEnd

        svc = _service()
        svc.start()
        front = ServeFrontEnd(svc, port=0).start()
        try:
            for payload in (b"", b"not json",
                            json.dumps({"nope": 1}).encode(),
                            json.dumps({"dcop": "::bad"}).encode()):
                req = urllib.request.Request(
                    front.url + "/solve", data=payload,
                    headers={"Content-Type": "application/json"})
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(req, timeout=10)
                assert err.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(front.url + "/result/zzz",
                                       timeout=10)
            assert err.value.code == 404
        finally:
            front.stop()
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# /healthz accelerator-probe surfacing


class TestHealthzProbeDiagnostics:
    def test_probe_failure_root_cause_in_health_body(self, monkeypatch):
        import os

        from pydcop_tpu.observability.server import health_verdict
        from pydcop_tpu.utils.cleanenv import DIAG_ENV, record_diag

        monkeypatch.setenv(DIAG_ENV, "[]")
        assert "accelerator_probe" not in health_verdict()
        record_diag("probe", tag="t", attempt=1, of=1, ok=False,
                    error="timeout after 60s", seconds=60.0)
        record_diag("cpu_fallback", tag="t")
        verdict = health_verdict()
        probe = verdict["accelerator_probe"]
        assert probe["failures"] == 2
        assert probe["last_event"] == "cpu_fallback"
        assert any(e.get("error") == "timeout after 60s"
                   for e in probe["recent"])
        # Informational only: probe trouble never flips the status.
        assert verdict["status"] == "ok"
        assert os.environ[DIAG_ENV]  # log survives for later bodies

    def test_successful_probes_keep_body_small(self, monkeypatch):
        from pydcop_tpu.observability.server import health_verdict
        from pydcop_tpu.utils.cleanenv import DIAG_ENV, record_diag

        monkeypatch.setenv(DIAG_ENV, "[]")
        record_diag("probe", tag="t", attempt=1, of=1, ok=True,
                    error=None, seconds=1.0)
        assert "accelerator_probe" not in health_verdict()


# ------------------------------------------------------------------ #
# bench sentinel: serving metric tracked per backend


class TestSentinelServeMetric:
    def _write(self, path, rows):
        import os

        for i, row in enumerate(rows, 1):
            with open(os.path.join(str(path),
                                   f"BENCH_r{i:02d}.json"),
                      "w", encoding="utf-8") as f:
                json.dump({"n": i, "parsed": row}, f)

    def test_serve_series_judged_separately(self, tmp_path):
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools"))
        import bench_sentinel

        steady = [900.0, 860.0, 910.0, 880.0, 895.0, 905.0]
        serve = [50.0, 52.0, 51.0, 49.0, 50.0, 14.0]  # 70% down
        self._write(tmp_path, [
            {"value": v, "backend": "cpu",
             "serve_problems_per_sec": s}
            for v, s in zip(steady, serve)
        ])
        report = bench_sentinel.run_check(str(tmp_path))
        # Headline series fine, serving series regressed: the serve
        # metric is tracked (and can fail the gate) on its own.
        assert report["series"]["cpu"]["verdict"] == "ok"
        assert report["series"]["serve:cpu"]["verdict"] == "regressed"
        assert report["failed"] is True
        assert any("serve[cpu]" in line for line in report["lines"])

    def test_history_without_serve_metric_unaffected(self, tmp_path):
        import os
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools"))
        import bench_sentinel

        steady = [900.0, 860.0, 910.0, 880.0]
        self._write(tmp_path, [
            {"value": v, "backend": "cpu"} for v in steady])
        report = bench_sentinel.run_check(str(tmp_path))
        assert report["failed"] is False
        assert "serve:cpu" not in report["series"]


# ------------------------------------------------------------------ #
# concurrent-client soak


class TestConcurrentSoak:
    N_CLIENTS = 6
    PER_CLIENT = 4

    def test_no_lost_or_duplicated_responses(self):
        """Every client gets exactly its own results back: ids are
        unique, every request finishes, and each response decodes the
        submitting client's own problem (variable names prove the
        structure; no cross-wiring)."""
        sizes = (10, 13)  # two structure bins, interleaved clients
        with _service(batch_window_s=0.05, max_batch=4,
                      max_queue=256) as svc:
            received = {}
            errors = []
            lock = threading.Lock()

            def client(cid):
                n = sizes[cid % len(sizes)]
                try:
                    for k in range(self.PER_CLIENT):
                        dcop = _instance(n, seed=cid * 100 + k)
                        rid = svc.submit(dcop, params=PARAMS)
                        res = svc.result(rid, wait=120)
                        with lock:
                            received[(cid, k)] = (rid, n, res)
                except Exception as exc:  # noqa: BLE001
                    with lock:
                        errors.append((cid, repr(exc)))

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(self.N_CLIENTS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
        assert not errors, errors
        assert len(received) == self.N_CLIENTS * self.PER_CLIENT
        ids = [rid for rid, _, _ in received.values()]
        assert len(set(ids)) == len(ids)  # no duplicated ids
        for (cid, k), (rid, n, res) in received.items():
            assert res is not None, f"lost response {cid}/{k}"
            assert res["status"] == "FINISHED"
            assert res["id"] == rid
            # The assignment covers exactly this client's variables.
            assert set(res["assignment"]) == {
                f"v{i}" for i in range(n)}
        # Ledger: everything completed, nothing failed.
        assert svc.completed >= self.N_CLIENTS * self.PER_CLIENT
        assert svc.failed == 0

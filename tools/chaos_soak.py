"""Chaos soak: a seeded scenario matrix asserting global invariants.

The robustness analogue of ``make perf-smoke``: where the perf gate
proves the hot path is *fast*, this gate proves the runtime *heals* —
every scenario injects a distinct failure combination (message drop +
duplicate + delay, network partition with healing, silent agent kill,
engine guard trips, checkpoint corruption, serve-process crash with
journal replay, poison requests in a batched bin, device loss
mid-sharded-solve) and asserts the system-wide invariants that define
"self-healing":

- **valid assignment** — every variable ends with a value from its
  domain (a migrated computation kept working; nothing was lost);
- **monotone cycle counter** — progress never runs backwards in the
  observable record (trace ``engine_segment`` spans may rewind ONLY
  across an explicit ``recovery_rollback``);
- **no orphaned computations** — a killed agent's computations are
  re-hosted, not dropped (their variables still carry values);
- **health verdicts consistent with the kill schedule** — every
  injected kill is reported ``agent_dead`` within the configured miss
  bound, and scenarios with message faults but NO kill produce zero
  death verdicts (suspicion is allowed: that is the phi-accrual
  detector doing its job on a lossy link).

Every scenario is a pure function of the seed (fault decisions are
seeded per edge+index, heartbeat bounds are schedule-free, guard trips
are cycle-keyed), so a red run REPLAYS: the failure report prints the
scenario name, the seed and the trace file to hand to
``pydcop trace summary``.

Usage::

    python tools/chaos_soak.py                 # full matrix
    python tools/chaos_soak.py --quick         # make-test gate (~20 s)
    python tools/chaos_soak.py --scenarios 6   # first N scenarios
    python tools/chaos_soak.py --seed 7 --only kill_detected

``make chaos-soak`` runs the full matrix; ``make test`` wires the
``--quick`` device-side gate (fixed seed, ~20 s): engine guard
recovery, checkpoint corruption, guard purity, journal crash replay,
poison-bin bisection, shard-loss repartition, and the anomaly
postmortem (a guard trip with file tracing off must leave a
flight-recorder bundle whose tail holds the triggering instant).
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# The shard-trip scenario needs a multi-device mesh: force the
# 8-virtual-device CPU platform (same recipe as the root conftest)
# unless the caller already chose a device count.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from pydcop_tpu.algorithms import AlgorithmDef  # noqa: E402
from pydcop_tpu.dcop.dcop import DCOP  # noqa: E402
from pydcop_tpu.dcop.objects import (  # noqa: E402
    AgentDef,
    Domain,
    Variable,
)
from pydcop_tpu.dcop.relations import constraint_from_str  # noqa: E402
from pydcop_tpu.distribution.objects import Distribution  # noqa: E402

DEFAULT_SEED = int(os.environ.get("PYDCOP_CHAOS_SEED", "42"))


# ------------------------------------------------------------------ #
# fixtures


def coloring_dcop(n_agents=5, n_vars=4):
    """3-colorable chain: fault-free optimum cost is 0."""
    d = Domain("colors", "", ["R", "G", "B"])
    dcop = DCOP("soak", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(n_vars - 1):
        dcop.add_constraint(constraint_from_str(
            f"diff_{i}_{i + 1}",
            f"10 if v{i} == v{i + 1} else 0",
            [variables[i], variables[i + 1]],
        ))
    dcop.add_agents([
        AgentDef(f"a{i}", capacity=100, default_hosting_cost=i)
        for i in range(n_agents)
    ])
    return dcop


def variable_distribution():
    return Distribution({
        "a0": ["v0"], "a1": ["v1"], "a2": ["v2"], "a3": ["v3"],
        "a4": [],
    })


def ring_dcop(n_vars=6):
    d = Domain("c", "", list(range(3)))
    dcop = DCOP("soak_ring", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)] + [(0, 3)]
    for i, j in edges:
        dcop.add_constraint(constraint_from_str(
            f"c{i}_{j}", f"10 if v{i} == v{j} else 0",
            [variables[i], variables[j]],
        ))
    return dcop


# ------------------------------------------------------------------ #
# invariants


def assert_valid_assignment(dcop, assignment):
    """Every variable valued, every value in its domain."""
    for name, variable in dcop.variables.items():
        assert name in assignment, f"variable {name} has NO value " \
            "(orphaned computation?)"
        value = assignment[name]
        assert value in list(variable.domain), \
            f"variable {name} = {value!r} outside its domain"


def assert_health_consistent(health, killed):
    """Dead verdicts == the injected kill schedule, exactly."""
    dead = set(health["dead"])
    assert dead == set(killed), (
        f"health verdicts inconsistent with kill schedule: "
        f"dead={sorted(dead)} killed={sorted(killed)}"
    )


def assert_monotone_segments(trace_path):
    """Engine segment cycles never rewind except across an explicit
    recovery rollback — the monotone-progress invariant."""
    from pydcop_tpu.observability.trace import load_trace_file

    events = sorted(
        (e for e in load_trace_file(trace_path)
         if e.get("name") in ("engine_segment", "recovery_rollback")),
        key=lambda e: e["ts"],
    )
    last_cycle = -1
    for ev in events:
        if ev["name"] == "recovery_rollback":
            last_cycle = -1  # an announced rewind resets the floor
            continue
        start = int(ev.get("args", {}).get("from_cycle", 0))
        assert start >= last_cycle, (
            f"cycle counter rewound without a rollback: segment from "
            f"cycle {start} after cycle {last_cycle}"
        )
        last_cycle = start
    return events


# ------------------------------------------------------------------ #
# scenarios — each returns a dict of observations, raises on failure


def _thread_chaos(seed, trace, *, plan, health=True, algo=None,
                  timeout=20):
    from pydcop_tpu.infrastructure.run import solve_with_agents
    from pydcop_tpu.observability import ObservabilitySession
    from pydcop_tpu.resilience.health import HealthConfig

    dcop = coloring_dcop()
    algo = algo or AlgorithmDef.build_with_default_param(
        "adsa", {"stop_cycle": 40, "period": 0.05}, mode="min")
    config = HealthConfig() if health else None
    with ObservabilitySession(trace, "chrome"):
        res = solve_with_agents(
            dcop, algo, distribution=variable_distribution(),
            timeout=timeout, fault_plan=plan, health_config=config,
        )
    assert_valid_assignment(dcop, res["assignment"])
    assert res.get("cycles", 0) > 0, "no cycle ever completed"
    return res


def scenario_kill_detected(seed, trace):
    """Silent kill mid-run: the heartbeat monitor (not the injector)
    must detect the death and the repair path must migrate the
    victim's computation."""
    from pydcop_tpu.resilience.faults import CrashEvent, FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, crashes=(CrashEvent("a1", 5),), replicas=2,
    ), timeout=45)
    assert res["killed_agents"] == ["a1"]
    assert_health_consistent(res["health"], ["a1"])
    assert res["status"] == "FINISHED", f"run ended {res['status']}"
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"dead": res["health"]["dead"], "cost": res["cost"]}


def scenario_drop_dup_delay(seed, trace):
    """Lossy-but-alive links: drop+dup+delay with NO kill must
    converge to the fault-free cost with ZERO death verdicts
    (suspicion allowed — that is the detector's designed response)."""
    from pydcop_tpu.resilience.faults import FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, drop=0.10, duplicate=0.05, delay=0.05,
        delay_time=0.02,
    ))
    stats = res["fault_stats"]
    assert stats["dropped"] > 0, "no fault injected — not a chaos run"
    assert_health_consistent(res["health"], [])
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"fault_stats": stats,
            "suspects": [v for v in res["health"]["verdicts"]
                         if v["status"] == "suspect"]}


def scenario_delay_only_no_death(seed, trace):
    """Pure delay (30%): heartbeats arrive late, never never-again —
    zero death verdicts."""
    from pydcop_tpu.resilience.faults import FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, delay=0.30, delay_time=0.05,
    ))
    assert_health_consistent(res["health"], [])
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"verdicts": len(res["health"]["verdicts"])}


def scenario_partition_heal(seed, trace):
    """A partition splits the chain mid-problem, then HEALS (per-edge
    index bound): the run must reconverge to the fault-free cost after
    the heal — the assertion PR-1's permanent partitions could never
    make."""
    from pydcop_tpu.resilience.faults import FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed,
        partitions=(frozenset({"a0", "a1"}),
                    frozenset({"a2", "a3", "a4"})),
        partition_heal_index=8,
    ), timeout=30)
    assert res["fault_stats"]["partitioned"] > 0, \
        "partition never blocked a message"
    assert_health_consistent(res["health"], [])
    assert res["cost"] == 0, (
        f"no reconvergence after partition heal: cost {res['cost']}")
    return {"partitioned": res["fault_stats"]["partitioned"]}


def scenario_drop_plus_kill(seed, trace):
    """Combined loss + silent kill: detection and repair under a lossy
    network."""
    from pydcop_tpu.resilience.faults import CrashEvent, FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, drop=0.10, crashes=(CrashEvent("a2", 5),),
        replicas=2,
    ), timeout=45)
    assert res["killed_agents"] == ["a2"]
    assert_health_consistent(res["health"], ["a2"])
    assert res["status"] == "FINISHED", f"run ended {res['status']}"
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"dead": res["health"]["dead"]}


def scenario_guard_trip_device(seed, trace):
    """Injected guard trip on a device solve: rollback + recovery must
    appear in the exported trace, the cycle counter may only rewind
    across the rollback, and the healed run still converges to a valid
    assignment."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.observability import ObservabilitySession
    from pydcop_tpu.resilience.recovery import RecoveryPolicy

    dcop = ring_dcop()
    with ObservabilitySession(trace, "chrome"):
        res = build_engine(dcop, {}).run_checkpointed(
            max_cycles=120, segment_cycles=7,
            recovery=RecoveryPolicy(trip_cycles=(14,),
                                    noise_seed=seed),
        )
    assert res.metrics["guard_trips"] == 1
    assert res.metrics["recovery_attempts"] == 1
    assert res.converged, "recovered run failed to converge"
    assert_valid_assignment(dcop, res.assignment)
    events = assert_monotone_segments(trace)
    names = {e["name"] for e in events}
    assert "recovery_rollback" in names, \
        "recovery span missing from exported trace"
    return {"trace_events": len(events),
            "actions": res.metrics["recovery_actions"]}


def scenario_guard_noop_device(seed, trace):
    """Guard armed, nothing injected: the guarded trajectory must be
    bit-identical to the unguarded one (guards are pure reads)."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.resilience.recovery import RecoveryPolicy

    dcop = ring_dcop()
    ref = build_engine(dcop, {}).run_checkpointed(
        max_cycles=120, segment_cycles=7)
    res = build_engine(dcop, {}).run_checkpointed(
        max_cycles=120, segment_cycles=7, recovery=RecoveryPolicy())
    assert res.metrics["guard_trips"] == 0
    assert res.assignment == ref.assignment, \
        "guarded run diverged from unguarded with no faults"
    assert res.cycles == ref.cycles
    assert_valid_assignment(dcop, res.assignment)
    return {"cycles": res.cycles}


def scenario_decimation_guard_trip(seed, trace):
    """Guard trip mid-decimation (ISSUE 10): the rollback must restore
    the CLAMP SET together with the snapshot — resuming the
    rolled-back messages under a stale (newer) active-edge mask would
    silently solve a different problem.  Asserted: the trip and the
    clamp-set rollback both happened, per-segment decimated counts are
    monotone EXCEPT exactly across the rollback (the decimation
    analogue of the monotone-cycle invariant), the healed run still
    fixes every variable and ends with a valid assignment."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.engine.runner import DecimationPlan
    from pydcop_tpu.resilience.recovery import RecoveryPolicy

    dcop = ring_dcop()

    class FixedCountProbe:
        """Records the engine's decimated count per validated
        segment (on_segment fires only for validated states)."""

        def __init__(self, decim_run_ref):
            self.counts = []
            self._ref = decim_run_ref

        def on_segment(self, state, values, run_s, compile_s):
            self.counts.append(int(self._ref[0].fixed.sum())
                               if self._ref[0] is not None else 0)

    engine = build_engine(dcop, {})
    # Reach into the run via a mutable ref the probe reads: the
    # engine constructs its _DecimationRun internally.
    ref = [None]
    orig_run = engine.run_checkpointed

    def run_with_ref(**kw):
        import pydcop_tpu.engine.runner as runner_mod

        orig_cls = runner_mod._DecimationRun

        class Capturing(orig_cls):
            def __init__(self, *a, **k):
                super().__init__(*a, **k)
                ref[0] = self

        runner_mod._DecimationRun = Capturing
        try:
            return orig_run(**kw)
        finally:
            runner_mod._DecimationRun = orig_cls

    probe = FixedCountProbe(ref)
    res = run_with_ref(
        max_cycles=400, segment_cycles=10,
        decimation=DecimationPlan(frac_per_round=0.25,
                                  cycles_per_round=10),
        recovery=RecoveryPolicy(trip_cycles=(25,), noise_seed=seed),
        probe=probe,
    )
    assert res.metrics["guard_trips"] == 1
    assert res.metrics["recovery_attempts"] == 1
    assert res.metrics["decimation_rollbacks"] == 1, \
        "guard trip did not roll the clamp set back with the snapshot"
    assert res.metrics["decimated_vars"] == len(dcop.variables), \
        "healed decimated run left variables unclamped"
    assert res.metrics["decimated_fraction"] == 1.0
    assert res.metrics["active_edges"] == 0
    assert_valid_assignment(dcop, res.assignment)
    # Monotone-decimation invariant: the validated per-segment counts
    # never decrease (a decrease would mean a stale mask leaked past
    # a rollback into a validated segment).
    counts = probe.counts
    assert all(b >= a for a, b in zip(counts, counts[1:])), \
        f"validated decimated counts ran backwards: {counts}"
    return {"decimated": res.metrics["decimated_vars"],
            "rounds": res.metrics["decimation_rounds"],
            "segment_counts": counts}


def scenario_checkpoint_corruption(seed, trace):
    """Torn-write simulation: truncate the newest snapshot mid-file;
    resume must fall back to the previous VALID snapshot and still
    reproduce the uninterrupted run; retention keeps exactly N."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.resilience.checkpoint import (
        CheckpointManager,
        resume_from_checkpoint,
    )

    dcop = ring_dcop()
    ref = build_engine(dcop, {}).run(max_cycles=120)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        manager = CheckpointManager(ckpt_dir, every=5, keep=2)
        build_engine(dcop, {}).run_checkpointed(
            max_cycles=120, manager=manager, max_segments=3)
        on_disk = manager.checkpoints()
        assert len(on_disk) == 2, (
            f"retention kept {len(on_disk)} snapshots, wanted "
            f"exactly 2")
        newest = on_disk[-1][1]
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 2)
        res = resume_from_checkpoint(
            build_engine(dcop, {}), manager, max_cycles=120)
        assert res.metrics["resumed_from_cycle"] == on_disk[-2][0], \
            "resume did not fall back to the previous valid snapshot"
        assert res.assignment == ref.assignment
        assert res.cycles == ref.cycles
        assert_valid_assignment(dcop, res.assignment)
        return {"resumed_from": res.metrics["resumed_from_cycle"]}


def _serve_instance(n_vars, seed):
    """Ring coloring with seeded random tables; carries an agent so
    it survives the journal's dcop_yaml round-trip."""
    import numpy as np

    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"soak_srv_{n_vars}_{seed}", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n_vars):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % n_vars]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def scenario_serve_journal_replay(seed, trace):
    """Crash-equivalent journal (accepted records, one pre-crash
    completion, a torn tail) + a ``recover=True`` service start:
    exactly the unfinished requests replay through the normal queue
    and complete — zero acknowledged requests lost — and the replay
    is announced in the trace (``serve_replay`` span)."""
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.observability import ObservabilitySession
    from pydcop_tpu.serving.journal import (
        RequestJournal,
        accepted_record,
        completed_record,
    )
    from pydcop_tpu.serving.service import SolveService

    params = {"max_cycles": 40}
    with tempfile.TemporaryDirectory() as journal_dir:
        jnl = RequestJournal(journal_dir)
        dcops = {}
        for i in range(5):
            rid = f"crash{i}"
            dcops[rid] = _serve_instance(8, seed * 100 + i)
            jnl.append(accepted_record(
                rid, dcop_yaml(dcops[rid]), params))
        jnl.append(completed_record("crash0", "FINISHED"))
        jnl.close()
        with open(jnl.path, "ab") as f:
            f.write(b"\x00\x00\x00\x20torn-mid-append")  # kill -9
        svc = SolveService(journal_dir=journal_dir, recover=True,
                           batch_window_s=0.05, max_batch=8)
        with ObservabilitySession(trace, "chrome"):
            svc.start()
            try:
                for rid in ("crash1", "crash2", "crash3", "crash4"):
                    result = svc.result(rid, wait=60.0)
                    assert result is not None \
                        and result["status"] == "FINISHED", \
                        f"replayed request {rid} lost after crash"
                    assert_valid_assignment(dcops[rid],
                                            result["assignment"])
                assert svc.replayed == 4, \
                    f"replayed {svc.replayed}, wanted exactly 4 " \
                    "(the pre-crash completion must not resurrect)"
                try:
                    svc.result("crash0")
                    raise AssertionError(
                        "completed-before-crash request resurrected")
                except KeyError:
                    pass
            finally:
                svc.stop(drain=False)
        from pydcop_tpu.observability.trace import load_trace_file

        names = {e["name"] for e in load_trace_file(trace)}
        assert "serve_replay" in names, \
            "serve_replay span missing from exported trace"
        return {"replayed": 4, "torn_tail": "truncated"}


def scenario_session_replay(seed, trace):
    """Crash-equivalent SESSION journal (ISSUE 13): an open record,
    3 acked event batches and a torn tail, no close — a
    ``recover=True`` start must rebuild the session's engine, apply
    every journaled batch, re-converge to EXACTLY the uninterrupted
    replay's final cost, announce the replay in the trace
    (``session_replay`` span), and a close must retire the session
    so a second recovery has nothing to resurrect."""
    import numpy as np

    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine.dynamic import build_dynamic_engine
    from pydcop_tpu.observability import ObservabilitySession
    from pydcop_tpu.serving.sessions import apply_event_batch
    from pydcop_tpu.serving.journal import (
        RequestJournal,
        session_event_record,
        session_open_record,
    )
    from pydcop_tpu.serving.service import SolveService

    rng = np.random.default_rng(seed)
    params = {"noise": 0.01, "stability": 0.001,
              "max_cycles": 500, "segment_cycles": 100}
    # Path topology: max-sum is exact there, so cost equality with
    # the uninterrupted run is a hard assertion, not a tolerance.
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"soak_sess_{seed}", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(10)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(9):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    batches = [
        [{"type": "change_factor", "name": f"c{int(rng.integers(9))}",
          "table": rng.integers(0, 10, size=(3, 3))
          .astype(float).tolist()}]
        for _ in range(3)
    ]
    # Uninterrupted reference through the same engine machinery.
    ref = build_dynamic_engine(dcop, params)
    ref.run(max_cycles=params["max_cycles"])
    for batch in batches:
        _applied, _touched, error = apply_event_batch(ref, batch)
        assert error is None, f"reference batch failed: {error}"
        ref.run(max_cycles=params["max_cycles"])
    expected = ref.cost(
        ref.run(max_cycles=params["max_cycles"]).assignment)

    with tempfile.TemporaryDirectory() as journal_dir:
        jnl = RequestJournal(journal_dir)
        jnl.append(session_open_record(
            "crash_sess", dcop_yaml(dcop), params))
        for i, batch in enumerate(batches):
            jnl.append(session_event_record("crash_sess", i + 1,
                                            batch))
        jnl.close()
        with open(jnl.path, "ab") as f:
            f.write(b"\x00\x00\x00\x20torn-mid-append")  # kill -9
        svc = SolveService(journal_dir=journal_dir, recover=True,
                           batch_window_s=0.05, max_batch=8)
        with ObservabilitySession(trace, "chrome"):
            svc.start()
            try:
                status = svc.sessions.status("crash_sess")
                assert status["seq"] == 3 \
                    and status["applied_seq"] == 3, \
                    f"acked batches lost in replay: {status}"
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    status = svc.sessions.status("crash_sess")
                    last = status["last"]
                    if last and last.get("converged"):
                        break
                    time.sleep(0.05)
                final = svc.sessions.close("crash_sess")
                assert final["cost"] == expected, \
                    f"recovered session cost {final['cost']} != " \
                    f"uninterrupted {expected}"
            finally:
                svc.stop(drain=False)
        svc2 = SolveService(journal_dir=journal_dir, recover=True,
                            batch_window_s=0.05)
        svc2.start()
        try:
            try:
                svc2.sessions.status("crash_sess")
                raise AssertionError(
                    "closed session resurrected on second recovery")
            except KeyError:
                pass
        finally:
            svc2.stop(drain=False)
    from pydcop_tpu.observability.trace import load_trace_file

    names = {e["name"] for e in load_trace_file(trace)}
    assert "session_replay" in names, \
        "session_replay span missing from exported trace"
    return {"replayed_batches": 3, "final_cost": expected}


def scenario_serve_poison_bin(seed, trace):
    """One poison request in a bin of 6: the failed dispatch BISECTS
    — the poison request fails alone, every bin-mate succeeds, the
    retries are accounted, and the breaker never opens."""
    from pydcop_tpu.serving.service import SolveService

    svc = SolveService(batch_window_s=0.3, max_batch=8)
    svc.start()
    real = svc._run_batch
    poison = set()

    def poisoned(reqs, params):
        if any(r.id in poison for r in reqs):
            raise RuntimeError("poison request in batch")
        return real(reqs, params)

    svc._run_batch = poisoned
    try:
        rids = [svc.submit(_serve_instance(8, seed * 10 + i),
                           params={"max_cycles": 40})
                for i in range(6)]
        poison.add(rids[seed % 6])
        statuses = {}
        for rid in rids:
            result = svc.result(rid, wait=60.0)
            assert result is not None, f"request {rid} hung"
            statuses[rid] = result["status"]
        assert statuses[rids[seed % 6]] == "ERROR", \
            "poison request must fail"
        mates = [r for r in rids if r != rids[seed % 6]]
        assert all(statuses[r] == "FINISHED" for r in mates), (
            "bin-mates of the poison request failed too: "
            f"{statuses}")
        assert svc.dispatch_retries > 0, \
            "bisection never retried (wholesale failure?)"
        assert svc.admission.breaker.state != "open", \
            "isolated poison failure opened the breaker"
        return {"retries": svc.dispatch_retries,
                "isolated": rids[seed % 6]}
    finally:
        svc.stop(drain=False)


def scenario_shard_trip_repartition(seed, trace):
    """Injected device loss mid-sharded-solve: rollback +
    re-partition onto the survivors, with the SAME final assignment
    and cost as the untripped run, the repartition visible in the
    trace, and the cycle counter monotone except across the
    announced rollback."""
    import numpy as np

    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.observability import ObservabilitySession
    from pydcop_tpu.resilience.recovery import RecoveryPolicy

    rng = np.random.default_rng(seed)
    d = Domain("d", "", [0, 1, 2])
    dcop = DCOP("soak_shard", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(20)]
    for v in vs:
        dcop.add_variable(v)
    seen, k = set(), 0
    while k < 30:
        i, j = rng.choice(20, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[key[0]], vs[key[1]]],
            rng.integers(0, 10, size=(3, 3)), name=f"c{k}"))
        k += 1
    ref = build_engine(dcop, {}, shards=2).run_checkpointed(
        max_cycles=60, segment_cycles=10)
    with ObservabilitySession(trace, "chrome"):
        res = build_engine(dcop, {}, shards=2).run_checkpointed(
            max_cycles=60, segment_cycles=10,
            recovery=RecoveryPolicy(trip_shard=((20, seed % 2),)))
    assert res.assignment == ref.assignment, \
        "repartitioned recovery diverged from the untripped solve"
    assert_valid_assignment(dcop, res.assignment)
    m = res.metrics
    assert m["shard_losses"] == 1 and m["repartitions"] == 1
    assert m["recovery_attempts"] == 0, \
        "a device loss must not consume the numerics restart budget"
    assert m["shard_recovery_s"] > 0
    events = assert_monotone_segments(trace)
    rollbacks = [e for e in events
                 if e["name"] == "recovery_rollback"]
    assert any(e["args"].get("action") == "repartition"
               for e in rollbacks), \
        "repartition rollback missing from exported trace"
    return {"lost_shard": seed % 2,
            "shard_recovery_s": m["shard_recovery_s"]}


def scenario_replica_kill(seed, trace):
    """ISSUE 15: SIGKILL one of two fleet replicas mid-burst.  Every
    202-acked request must complete through the router — the survivors
    keep serving while the dead replica's journal segment is handed to
    its restarted replacement and replayed — zero acknowledged
    requests lost, and the fleet SIGTERM-drains clean (every worker
    exit 0)."""
    import json
    import signal as signal_mod
    import urllib.error
    import urllib.request

    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    journal_dir = tempfile.mkdtemp(prefix="soak_fleet_")
    handle = api.serve(port=0, replicas=2, batch_window_s=0.25,
                       max_batch=8, journal_dir=journal_dir,
                       heartbeat_s=0.15)
    try:
        url = handle.url

        def post(payload):
            req = urllib.request.Request(
                url + "/solve", data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as err:
                return err.code, json.loads(err.read())

        acked, dcops = [], {}
        for i in range(10):
            dcop = _serve_instance(10, seed * 1000 + i)
            status, body = post({"dcop": dcop_yaml(dcop),
                                 "params": {"max_cycles": 150}})
            assert status == 202, f"burst request {i}: {status}"
            acked.append(body["id"])
            dcops[body["id"]] = dcop
        # Mid-burst: batch windows still open on both replicas.
        victim = handle.router.replicas[seed % 2]
        os.kill(victim.proc.pid, signal_mod.SIGKILL)

        # The survivors must keep admitting DURING the recovery.
        extra = _serve_instance(10, seed * 1000 + 99)
        status, body = post({"dcop": dcop_yaml(extra),
                             "params": {"max_cycles": 150}})
        assert status in (200, 202, 503), \
            f"router wedged during replica death ({status})"
        if status == 202:
            acked.append(body["id"])
            dcops[body["id"]] = extra

        done = {}
        deadline = time.monotonic() + 120
        while len(done) < len(acked) \
                and time.monotonic() < deadline:
            for rid in acked:
                if rid in done:
                    continue
                try:
                    with urllib.request.urlopen(
                            url + f"/result/{rid}",
                            timeout=10) as resp:
                        if resp.status == 200:
                            done[rid] = json.loads(resp.read())
                except (urllib.error.HTTPError, OSError):
                    pass
            time.sleep(0.1)
        lost = sorted(set(acked) - set(done))
        assert not lost, \
            f"{len(lost)} acked request(s) lost to the SIGKILL: " \
            f"{lost}"
        assert all(r["status"] == "FINISHED"
                   for r in done.values()), \
            {k: v["status"] for k, v in done.items()
             if v["status"] != "FINISHED"}
        for rid in acked[:2]:
            assert_valid_assignment(dcops[rid],
                                    done[rid]["assignment"])
        assert victim.restarts == 1, \
            f"victim restarted {victim.restarts} times, wanted 1"
        stats = handle.router.stats()
        assert stats["deaths"] == 1 and stats["up"] == 2
    finally:
        summary = handle.stop()
        shutil.rmtree(journal_dir, ignore_errors=True)
    exits = [w["exit"] for w in summary["workers"]]
    assert exits == [0, 0], \
        f"fleet SIGTERM drain not clean: exits {exits}"
    return {"acked": len(acked), "completed": len(done),
            "victim": victim.index,
            "deaths": stats["deaths"]}


def _session_chaos_problem(seed):
    """Path-topology dynamic session problem + event batches +
    uninterrupted reference cost.  Path topology: max-sum is exact
    there, so cost equality across a migration/kill is a hard
    assertion, not a tolerance (same recipe as session_replay)."""
    import numpy as np

    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.engine.dynamic import build_dynamic_engine
    from pydcop_tpu.serving.sessions import apply_event_batch

    rng = np.random.default_rng(seed)
    params = {"noise": 0.01, "stability": 0.001, "max_cycles": 500}
    d = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"soak_mig_{seed}", objective="min")
    vs = [Variable(f"v{i}", d) for i in range(10)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(9):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    batches = [
        [{"type": "change_factor",
          "name": f"c{int(rng.integers(9))}",
          "table": rng.integers(0, 10, size=(3, 3))
          .astype(float).tolist()}]
        for _ in range(5)
    ]
    ref = build_dynamic_engine(dcop, params)
    ref.run(max_cycles=params["max_cycles"])
    for batch in batches:
        _applied, _touched, error = apply_event_batch(ref, batch)
        assert error is None, f"reference batch failed: {error}"
        ref.run(max_cycles=params["max_cycles"])
    expected = ref.cost(
        ref.run(max_cycles=params["max_cycles"]).assignment)
    return dcop, params, batches, expected


def _fleet_request(url, method="GET", payload=None, timeout=60):
    import json
    import urllib.error
    import urllib.request

    data = (json.dumps(payload).encode()
            if payload is not None else None)
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def _patch_until_acked(url, sid, batch, deadline_s=90):
    """PATCH with the elastic-fleet client contract: 409 means the
    session is frozen MIGRATING (retry lands on the new owner through
    the repointed pin), 503 means the owner is being
    recovered/adopted.  Both resolve; anything else is a failure."""
    deadline = time.monotonic() + deadline_s
    while True:
        status, out = _fleet_request(
            url + f"/session/{sid}/events", "PATCH",
            {"events": batch, "wait": True, "timeout": 30.0})
        if status == 200:
            return out
        assert status in (409, 503), \
            f"PATCH failed non-retryably: {status} {out}"
        assert time.monotonic() < deadline, \
            f"PATCH never recovered: last {status} {out}"
        time.sleep(0.2)


def scenario_session_migrate(seed, trace):
    """ISSUE 16 live migration under PATCH traffic: a warm session is
    migrated between replicas (operator ``POST /admin/migrate``)
    while a client keeps streaming event batches.  Every acked batch
    must survive the move — the final cost equals the uninterrupted
    single-engine run on integer tables (hard equality, path
    topology) — and the router pin must point at the new owner."""
    import threading

    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    dcop, params, batches, expected = _session_chaos_problem(seed)
    journal_dir = tempfile.mkdtemp(prefix="soak_mig_")
    handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                       journal_dir=journal_dir, heartbeat_s=0.15)
    try:
        url = handle.url
        status, body = _fleet_request(
            url + "/session", "POST",
            {"dcop": dcop_yaml(dcop), "params": params})
        assert status == 201, f"open failed: {status} {body}"
        sid = body["session_id"]
        _patch_until_acked(url, sid, batches[0])
        _patch_until_acked(url, sid, batches[1])
        source = handle.router.pinned(
            sid, handle.router._session_pins)

        migrate_result = {}

        def _migrate():
            migrate_result["reply"] = _fleet_request(
                url + "/admin/migrate", "POST",
                {"session_id": sid}, timeout=120)

        mover = threading.Thread(target=_migrate, daemon=True)
        mover.start()
        # Live PATCH traffic DURING the move: the freeze window 409s,
        # the retry lands on whichever side owns the session.
        for batch in batches[2:]:
            _patch_until_acked(url, sid, batch)
        mover.join(timeout=120)
        assert not mover.is_alive(), "/admin/migrate hung"
        status, out = migrate_result["reply"]
        assert status == 200, f"migrate failed: {status} {out}"
        target = handle.router.pinned(
            sid, handle.router._session_pins)
        assert target.index != source.index, \
            "router pin did not move with the session"

        st = {}
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            _code, st = _fleet_request(url + f"/session/{sid}")
            last = st.get("last")
            if last and last.get("converged"):
                break
            time.sleep(0.05)
        assert st.get("applied_seq") == len(batches), \
            f"acked batches lost across migration: {st}"
        status, final = _fleet_request(url + f"/session/{sid}",
                                       "DELETE")
        assert status == 200, f"close failed: {status} {final}"
        assert final["cost"] == expected, \
            f"migrated session cost {final['cost']} != " \
            f"uninterrupted {expected}"
        stats = handle.router.stats()
        assert stats["migrations"] == 1, stats["migrations"]
    finally:
        handle.stop()
        shutil.rmtree(journal_dir, ignore_errors=True)
    return {"final_cost": expected,
            "from": source.index, "to": target.index}


def scenario_host_kill(seed, trace):
    """ISSUE 16 host death: a 4-replica fleet striped over 2
    simulated hosts loses ALL of host0's replicas (SIGKILL) mid-burst
    with a warm session pinned somewhere.  Zero acked solve requests
    lost (journal replay through the restarted slots), zero acked
    session events lost (the session is adopted by a survivor if its
    owner died), and the fleet heals back to 4 up."""
    import signal as signal_mod

    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    dcop, params, batches, expected = _session_chaos_problem(seed)
    journal_dir = tempfile.mkdtemp(prefix="soak_hostkill_")
    handle = api.serve(port=0, replicas=4, hosts=2,
                       batch_window_s=0.25, max_batch=8,
                       journal_dir=journal_dir, heartbeat_s=0.15)
    try:
        url = handle.url
        status, body = _fleet_request(
            url + "/session", "POST",
            {"dcop": dcop_yaml(dcop), "params": params})
        assert status == 201, f"open failed: {status} {body}"
        sid = body["session_id"]
        _patch_until_acked(url, sid, batches[0])
        _patch_until_acked(url, sid, batches[1])

        acked = []
        for i in range(10):
            inst = _serve_instance(10, seed * 1000 + i)
            status, body = _fleet_request(
                url + "/solve",
                "POST", {"dcop": dcop_yaml(inst),
                         "params": {"max_cycles": 150}})
            assert status == 202, f"burst request {i}: {status}"
            acked.append(body["id"])

        # Mid-burst: kill EVERY replica of host0 at once.
        victims = [r for r in handle.router.replicas
                   if r.host_id == "host0"]
        assert len(victims) == 2, \
            [r.host_id for r in handle.router.replicas]
        for victim in victims:
            os.kill(victim.proc.pid, signal_mod.SIGKILL)

        done = {}
        deadline = time.monotonic() + 180
        while len(done) < len(acked) \
                and time.monotonic() < deadline:
            for rid in acked:
                if rid in done:
                    continue
                code, out = _fleet_request(
                    url + f"/result/{rid}", timeout=10)
                if code == 200:
                    done[rid] = out
            time.sleep(0.1)
        lost = sorted(set(acked) - set(done))
        assert not lost, \
            f"{len(lost)} acked request(s) lost to the host kill: " \
            f"{lost}"
        assert all(r["status"] == "FINISHED"
                   for r in done.values()), \
            {k: v["status"] for k, v in done.items()
             if v["status"] != "FINISHED"}

        # Every acked session event survived — through adoption when
        # the owner died with its host, in place otherwise.
        _patch_until_acked(url, sid, batches[2], deadline_s=180)
        _code, st = _fleet_request(url + f"/session/{sid}")
        assert st.get("seq") == 3 and st.get("applied_seq") == 3, \
            f"acked session events lost: {st}"
        status, final = _fleet_request(url + f"/session/{sid}",
                                       "DELETE")
        assert status == 200, f"close failed: {status} {final}"

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if handle.router.up_count() == 4:
                break
            time.sleep(0.1)
        stats = handle.router.stats()
        assert stats["up"] == 4, \
            f"fleet never healed: {stats['up']}/4 up"
        assert stats["deaths"] == 2, stats["deaths"]
    finally:
        handle.stop()
        shutil.rmtree(journal_dir, ignore_errors=True)
    return {"acked": len(acked), "completed": len(done),
            "deaths": stats["deaths"],
            "session_events": st["applied_seq"]}


def scenario_fleet_partition_heal(seed, trace):
    """ISSUE 19 split-brain: a remote-joined replica owning a warm
    session is PARTITIONED (netfault blackhole) mid-PATCH-burst, the
    router declares it dead and ADOPTS the session onto a survivor
    (epoch bump), the partition heals — and the healed original is
    FENCED at the revival probe: its stale copy rejects direct writes
    with a structured 409, the surviving copy holds every acked
    batch, and the final cost equals the uninterrupted run (hard
    equality, path topology)."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving import netfault

    dcop, params, batches, expected = _session_chaos_problem(seed)
    journal_dir = tempfile.mkdtemp(prefix="soak_fpart_")
    remote_journal = tempfile.mkdtemp(prefix="soak_fpart_remote_")
    handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                       journal_dir=journal_dir, heartbeat_s=0.15)
    remote = api.serve(port=0, batch_window_s=0.05,
                       journal_dir=remote_journal)
    try:
        url = handle.url
        router = handle.router
        status, body = _fleet_request(
            url + "/session", "POST",
            {"dcop": dcop_yaml(dcop), "params": params})
        assert status == 201, f"open failed: {status} {body}"
        sid = body["session_id"]
        _patch_until_acked(url, sid, batches[0])

        remote_idx = router.register_remote(
            remote.url, host_id="hostB",
            journal_dir=remote_journal)["index"]
        status, out = _fleet_request(
            url + "/admin/migrate", "POST",
            {"session_id": sid, "target": remote_idx}, timeout=120)
        assert status == 200, f"migrate to remote failed: " \
                              f"{status} {out}"
        assert router.session_epoch(sid) == 2
        _patch_until_acked(url, sid, batches[1])
        _code, st = _fleet_request(remote.url + f"/session/{sid}")
        assert st.get("epoch") == 2, \
            f"migrated-in copy lost its epoch: {st}"

        # Sever router->remote.  The prober's verdict fires adoption
        # (the remote announced a reachable journal segment); PATCH
        # traffic sheds 503-with-retry until the pin repoints.
        netfault.install("link=*>hostB,blackhole=1,hold_s=0.05")
        _patch_until_acked(url, sid, batches[2], deadline_s=120)
        _patch_until_acked(url, sid, batches[3], deadline_s=120)
        survivor = router.pinned(sid, router._session_pins)
        assert survivor.index != remote_idx, \
            "session was not adopted off the partitioned replica"
        assert router.session_epoch(sid) >= 3
        injected = netfault.counters()
        assert injected.get("blackhole", 0) > 0, injected

        # Heal.  The revival probe must fence the stale copy BEFORE
        # any client byte can reach it.
        netfault.clear()
        deadline = time.monotonic() + 60
        fenced_st = {}
        while time.monotonic() < deadline:
            _code, fenced_st = _fleet_request(
                remote.url + f"/session/{sid}")
            if fenced_st.get("status") == "FENCED":
                break
            time.sleep(0.1)
        assert fenced_st.get("status") == "FENCED", \
            f"healed replica was not fenced: {fenced_st}"

        # Direct stale write to the healed original: structured 409.
        status, out = _fleet_request(
            remote.url + f"/session/{sid}/events", "PATCH",
            {"events": batches[4], "epoch": 2})
        assert status == 409 and out.get("stale_epoch") is True, \
            f"stale write not fenced: {status} {out}"
        assert out.get("session_epoch", 0) >= 2, out

        # The router-facing session keeps serving: last batch lands
        # on the survivor, nothing acked was lost or double-applied.
        _patch_until_acked(url, sid, batches[4])
        _code, st = _fleet_request(url + f"/session/{sid}")
        assert st.get("seq") == len(batches) \
            and st.get("applied_seq") == len(batches), \
            f"acked events lost/doubled across the partition: {st}"
        status, final = _fleet_request(url + f"/session/{sid}",
                                       "DELETE")
        assert status == 200, f"close failed: {status} {final}"
        assert final["cost"] == expected, \
            f"post-partition cost {final['cost']} != " \
            f"uninterrupted {expected}"
        stats = router.stats()
        assert stats["adopted_sessions"] >= 1, stats
    finally:
        netfault.clear()
        handle.stop()
        remote.stop()
        shutil.rmtree(journal_dir, ignore_errors=True)
        shutil.rmtree(remote_journal, ignore_errors=True)
    return {"final_cost": expected,
            "epoch": router.session_epoch(sid),
            "injected": injected}


def scenario_fleet_gray_failure(seed, trace):
    """ISSUE 19 gray failure: a replica whose link turns SLOW (500 ms
    injected delay, under the probe timeout) must be reported as a
    degraded/gray link on /healthz — and must NOT be declared dead
    (latency-aware probe scoring beats binary liveness).  Clearing
    the fault returns the fleet to ok."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving import netfault

    handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                       heartbeat_s=0.2)
    try:
        url = handle.url
        router = handle.router
        deaths0 = router.stats()["deaths"]
        netfault.install("link=router>replica-1,delay_ms=500")
        gray = {}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            _code, hz = _fleet_request(url + "/healthz", timeout=10)
            links = (hz.get("fleet") or {}).get("links") or []
            gray = next((l for l in links
                         if l.get("verdict") == "gray"), {})
            if hz.get("status") == "degraded" and gray:
                break
            time.sleep(0.1)
        assert gray, f"slow link never went gray: {hz}"
        assert gray["replica"] == 1, gray
        assert hz.get("status") == "degraded", hz
        assert (hz["fleet"].get("netfault_injected") or {}) \
            .get("delay", 0) > 0, hz
        assert router.stats()["deaths"] == deaths0, \
            "gray (slow-but-alive) replica was falsely killed"

        # Slow is not dead: a solve routed to the gray replica still
        # completes (the injected delay rides the forward too).
        inst = _serve_instance(8, seed)
        status, body = _fleet_request(
            url + "/solve", "POST",
            {"dcop": dcop_yaml(inst), "params": {"max_cycles": 80}})
        assert status == 202, f"solve under gray: {status} {body}"
        deadline = time.monotonic() + 60
        code, out = 0, {}
        while time.monotonic() < deadline:
            code, out = _fleet_request(
                url + f"/result/{body['id']}", timeout=10)
            if code == 200:
                break
            time.sleep(0.1)
        assert code == 200 and out["status"] == "FINISHED", \
            f"solve lost under gray link: {code} {out}"

        netfault.clear()
        deadline = time.monotonic() + 30
        hz = {}
        while time.monotonic() < deadline:
            _code, hz = _fleet_request(url + "/healthz", timeout=10)
            if hz.get("status") == "ok":
                break
            time.sleep(0.1)
        assert hz.get("status") == "ok", \
            f"fleet never recovered from gray: {hz}"
        assert router.stats()["deaths"] == deaths0
    finally:
        netfault.clear()
        handle.stop()
    return {"gray_probe_ms": gray.get("probe_ms"),
            "deaths": deaths0}


def scenario_fleet_retry_idempotent(seed, trace):
    """ISSUE 19 ambiguous-failure retry: the response to a forwarded
    /solve is LOST after the worker executed it (netfault
    lose_response).  The router's deadline-bounded retry redelivers
    to the SAME pinned replica; the worker dedupes on the
    router-minted id — the client sees one 202 and one result,
    exactly one execution, retries within the deadline budget."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving import netfault

    journal_dir = tempfile.mkdtemp(prefix="soak_fretry_")
    handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                       journal_dir=journal_dir, heartbeat_s=0.15)
    try:
        url = handle.url
        router = handle.router
        netfault.install(
            f"seed={seed};link=router>replica-*,path=/solve,"
            "lose_response=1.0,times=1")
        inst = _serve_instance(10, seed)
        t0 = time.monotonic()
        status, body = _fleet_request(
            url + "/solve", "POST",
            {"dcop": dcop_yaml(inst),
             "params": {"max_cycles": 120}, "deadline_s": 30.0})
        elapsed = time.monotonic() - t0
        assert status == 202, \
            f"solve not retried through lost response: " \
            f"{status} {body}"
        assert elapsed < 30.0, \
            f"retry blew the deadline budget: {elapsed:.1f}s"
        injected = netfault.counters()
        assert injected.get("lose_response", 0) == 1, injected

        deadline = time.monotonic() + 60
        code, out = 0, {}
        while time.monotonic() < deadline:
            code, out = _fleet_request(
                url + f"/result/{body['id']}", timeout=10)
            if code == 200:
                break
            time.sleep(0.1)
        assert code == 200 and out["status"] == "FINISHED", \
            f"result lost: {code} {out}"

        assert router.stats()["retries"] >= 1, router.stats()
        # Exactly one execution: the redelivery hit the worker's
        # dedupe table, not the solve queue.
        replica = router.pinned(body["id"])
        _code, wstats = _fleet_request(
            f"http://{replica.host}:{replica.port}/stats",
            timeout=10)
        assert wstats.get("deduped", 0) >= 1, wstats
    finally:
        netfault.clear()
        handle.stop()
        shutil.rmtree(journal_dir, ignore_errors=True)
    return {"retries": router.stats()["retries"],
            "deduped": wstats.get("deduped"),
            "elapsed_s": round(elapsed, 2)}


def scenario_forensics_under_faults(seed, trace):
    """ISSUE 20 forensics gate: a request whose response is LOST
    after execution (netfault lose_response) must be fully
    reconstructable from telemetry ALONE — ``GET
    /fleet/forensics/<id>`` shows one well-nested causal tree with
    the route pick, the retry hop, the dedupe hit on redelivery, and
    exactly ONE execute (``serve_dispatch``) span.  No log grepping,
    no worker /stats: the trace plane itself proves idempotency."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving import netfault

    journal_dir = tempfile.mkdtemp(prefix="soak_forensics_")
    handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                       journal_dir=journal_dir, heartbeat_s=0.15)
    try:
        url = handle.url
        netfault.install(
            f"seed={seed};link=router>replica-*,path=/solve,"
            "lose_response=1.0,times=1")
        inst = _serve_instance(10, seed)
        status, body = _fleet_request(
            url + "/solve", "POST",
            {"dcop": dcop_yaml(inst),
             "params": {"max_cycles": 120}, "deadline_s": 30.0})
        assert status == 202, \
            f"solve not retried through lost response: " \
            f"{status} {body}"
        rid = body["id"]
        deadline = time.monotonic() + 60
        code, out = 0, {}
        while time.monotonic() < deadline:
            code, out = _fleet_request(
                url + f"/result/{rid}", timeout=10)
            if code == 200:
                break
            time.sleep(0.1)
        assert code == 200 and out["status"] == "FINISHED", \
            f"result lost: {code} {out}"

        # Span shipping is async (bounded batches on a flush
        # interval): give the worker's shipper a few flushes before
        # judging the merged tree.
        def _nodes(roots):
            for node in roots:
                yield node
                yield from _nodes(node["children"])

        names, doc = set(), {}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            code, doc = _fleet_request(
                url + f"/fleet/forensics/{rid}", timeout=10)
            if code == 200:
                names = set(doc["names"])
                if {"router_retry", "serve_dedupe",
                        "serve_dispatch"} <= names:
                    break
            time.sleep(0.25)
        assert code == 200, f"forensics unavailable: {code} {doc}"
        assert doc["well_nested"], \
            f"forensics tree not well-nested: {sorted(names)}"
        assert "router_route_pick" in names, sorted(names)
        assert "router_retry" in names, \
            f"retry hop missing from the tree: {sorted(names)}"
        assert "netfault_injected" in names, \
            f"injected fault missing from the tree: {sorted(names)}"
        assert "serve_dedupe" in names, \
            f"dedupe hit missing from the tree: {sorted(names)}"
        flat = list(_nodes(doc["tree"]))
        executes = [n for n in flat
                    if n["name"] == "serve_dispatch"
                    and n["ph"] == "X"]
        assert len(executes) == 1, (
            f"forensics shows {len(executes)} executions of {rid} "
            "(idempotent forwarding demands exactly one)")
        retries = [n for n in flat if n["name"] == "router_retry"]
    finally:
        netfault.clear()
        handle.stop()
        shutil.rmtree(journal_dir, ignore_errors=True)
    return {"spans": doc["spans"], "instants": doc["instants"],
            "lanes": doc["lanes"], "retry_hops": len(retries),
            "well_nested": doc["well_nested"]}


def scenario_anomaly_postmortem(seed, trace):
    """ISSUE 9 anomaly path: an injected guard trip, with file
    tracing OFF and only the always-on flight recorder attached,
    must leave a postmortem bundle on disk whose event tail contains
    the triggering instant plus pre-anomaly engine context — the
    black box works precisely when nobody was tracing."""
    import glob
    import json

    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.observability.flight import FlightRecorder
    from pydcop_tpu.observability.trace import tracer
    from pydcop_tpu.resilience.recovery import RecoveryPolicy

    bundle_dir = tempfile.mkdtemp(prefix="soak_bundles_")
    prev = tracer.flight
    tracer.set_flight(FlightRecorder(events=512,
                                     bundle_dir=bundle_dir))
    try:
        assert not tracer.enabled, \
            "scenario requires file tracing OFF (black-box mode)"
        dcop = ring_dcop()
        res = build_engine(dcop, {}).run_checkpointed(
            max_cycles=120, segment_cycles=7,
            recovery=RecoveryPolicy(trip_cycles=(14,),
                                    noise_seed=seed))
    finally:
        tracer.set_flight(prev)
    assert res.metrics["guard_trips"] == 1
    assert res.converged and res.assignment
    assert_valid_assignment(dcop, res.assignment)
    bundles = glob.glob(
        os.path.join(bundle_dir, "bundle_guard_trip_*.json"))
    assert len(bundles) == 1, \
        f"expected exactly one guard-trip bundle, found {bundles}"
    with open(bundles[0], encoding="utf-8") as f:
        doc = json.load(f)
    tail_names = [e["name"] for e in doc["events"]]
    anomalies = [e for e in doc["events"] if e["name"] == "anomaly"]
    assert anomalies, \
        f"triggering instant missing from bundle tail: {tail_names}"
    assert anomalies[-1]["args"]["kind"] == "guard_trip"
    assert anomalies[-1]["args"]["cycle"] == 14
    assert "engine_segment" in tail_names, \
        "pre-anomaly engine context missing from the ring tail"
    for section in ("metrics", "healthz", "env",
                    "probe_diagnostics"):
        assert section in doc, f"bundle missing {section} section"
    return {"bundle": bundles[0],
            "tail_events": len(doc["events"])}


# Quick-gate ordering: the first 6 cover every failure class (kill
# detection, engine recovery, partition healing, lossy links,
# checkpoint corruption, guard purity).
SCENARIOS = [
    ("kill_detected", scenario_kill_detected),
    ("guard_trip_device", scenario_guard_trip_device),
    ("partition_heal", scenario_partition_heal),
    ("drop_dup_delay", scenario_drop_dup_delay),
    ("checkpoint_corruption", scenario_checkpoint_corruption),
    ("guard_noop_device", scenario_guard_noop_device),
    ("delay_only_no_death", scenario_delay_only_no_death),
    ("drop_plus_kill", scenario_drop_plus_kill),
    ("serve_journal_replay", scenario_serve_journal_replay),
    ("session_replay", scenario_session_replay),
    ("serve_poison_bin", scenario_serve_poison_bin),
    ("replica_kill", scenario_replica_kill),
    ("session_migrate", scenario_session_migrate),
    ("host_kill", scenario_host_kill),
    ("fleet_partition_heal", scenario_fleet_partition_heal),
    ("fleet_gray_failure", scenario_fleet_gray_failure),
    ("fleet_retry_idempotent", scenario_fleet_retry_idempotent),
    ("forensics_under_faults", scenario_forensics_under_faults),
    ("shard_trip_repartition", scenario_shard_trip_repartition),
    ("anomaly_postmortem", scenario_anomaly_postmortem),
    ("decimation_guard_trip", scenario_decimation_guard_trip),
]

# The `make test` gate (--quick): the DEVICE-SIDE failure classes —
# engine guard recovery, checkpoint corruption, guard purity, plus
# the three ISSUE-8 classes (journal crash replay, poison-bin
# bisection, shard-loss repartition) — chosen to finish in ~20 s.
# The thread-runtime scenarios (kills, partitions, lossy links) stay
# in the full matrix (`make chaos-soak`); their invariants also run
# in `make test` through tests/unit/test_resilience_battery.py and
# test_selfheal_battery.py.
QUICK_GATE = [
    "guard_trip_device",
    "checkpoint_corruption",
    "guard_noop_device",
    "serve_journal_replay",
    "session_replay",
    "serve_poison_bin",
    "replica_kill",
    "session_migrate",
    "host_kill",
    "fleet_partition_heal",
    "fleet_gray_failure",
    "fleet_retry_idempotent",
    "forensics_under_faults",
    "shard_trip_repartition",
    "anomaly_postmortem",
    "decimation_guard_trip",
]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--scenarios", type=int, default=0,
                        help="run only the first N scenarios "
                             "(0 = full matrix)")
    parser.add_argument("--quick", action="store_true",
                        help="the `make test` gate: the device-side "
                             "scenario subset (~20 s)")
    parser.add_argument("--seed", type=int, default=DEFAULT_SEED)
    parser.add_argument("--only", default=None,
                        help="run a single scenario by name (replay)")
    parser.add_argument("--out", default=None,
                        help="directory for per-scenario trace files "
                             "(default: a temp dir)")
    args = parser.parse_args(argv)

    selected = SCENARIOS
    if args.only:
        selected = [s for s in SCENARIOS if s[0] == args.only]
        if not selected:
            names = ", ".join(name for name, _ in SCENARIOS)
            print(f"unknown scenario {args.only!r}; have: {names}")
            return 2
    elif args.quick:
        selected = [s for s in SCENARIOS if s[0] in QUICK_GATE]
    elif args.scenarios:
        selected = SCENARIOS[:args.scenarios]

    out_dir = args.out or tempfile.mkdtemp(prefix="chaos_soak_")
    os.makedirs(out_dir, exist_ok=True)
    print(f"chaos soak: {len(selected)} scenario(s), "
          f"seed={args.seed}, traces in {out_dir}")
    failures = 0
    t_total = time.perf_counter()
    for name, fn in selected:
        trace = os.path.join(out_dir, f"{name}.trace.json")
        t0 = time.perf_counter()
        try:
            obs = fn(args.seed, trace)
        except Exception as e:
            failures += 1
            print(f"FAIL  {name} ({time.perf_counter() - t0:.1f}s): "
                  f"{e}")
            print(f"      replay: python tools/chaos_soak.py "
                  f"--seed {args.seed} --only {name} "
                  f"--out {out_dir}")
            print(f"      trace:  {trace}  "
                  f"(pydcop trace summary {trace})")
            continue
        print(f"ok    {name} ({time.perf_counter() - t0:.1f}s) {obs}")
    status = "FAIL" if failures else "PASS"
    print(f"chaos soak {status}: {len(selected) - failures}/"
          f"{len(selected)} scenarios in "
          f"{time.perf_counter() - t_total:.1f}s")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

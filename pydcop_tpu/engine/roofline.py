"""Achieved-FLOPs / HBM-traffic accounting for the device engine.

The VERDICT-mandated honesty layer for benchmark claims: given a
compiled graph we count, from the bucket shapes alone, the arithmetic
and memory traffic one MaxSum superstep performs (ops/maxsum.py
superstep), so bench results can report achieved FLOP/s, an MFU against
the chip's matmul peak, and — the meaningful roofline for this op mix —
HBM bandwidth utilization.

The counts are *models*, not profiler measurements: they assume XLA
fuses elementwise chains (each logical array is read/written once per
use) and count one FLOP per add/multiply/compare.  MaxSum's op mix is
min-plus gather/scatter on tiny minor dimensions, so it cannot use the
MXU at all; the MFU-vs-matmul-peak number is included because the
benchmark contract asks for it, and it is honestly tiny.

`hbm_util` is the meaningful efficiency number, but ONLY when the
problem is big enough that its working set actually streams from HBM:
when `working_set_bytes` fits comfortably in on-chip VMEM (most
problems below ~1M variables, including the 10k north-star bench), XLA
keeps all state resident across supersteps, actual HBM traffic is near
zero, and the byte model is a ceiling rather than a measurement —
`hbm_util` is then None with `vmem_resident: True`.  bench.py's 1M-var
scale leg exists precisely to measure the HBM-bound regime.

Peak numbers come from public chip specs, keyed on
`jax.devices()[0].device_kind` so each TPU generation gets its own
roofline; unknown kinds (and CPU backends) get `None` peaks and the
bench reports achieved numbers without a utilization claim.
"""

from typing import Dict, Optional, Tuple

from pydcop_tpu.engine.compile import CompiledFactorGraph

V5E_PEAK_FLOPS_BF16 = 197e12
V5E_HBM_BYTES_PER_S = 819e9

# device_kind -> (peak bf16 matmul FLOP/s, HBM bytes/s), public specs.
TPU_PEAKS: Dict[str, Tuple[float, float]] = {
    "TPU v4": (275e12, 1.2e12),
    "TPU v5 lite": (V5E_PEAK_FLOPS_BF16, V5E_HBM_BYTES_PER_S),
    "TPU v5e": (V5E_PEAK_FLOPS_BF16, V5E_HBM_BYTES_PER_S),
    "TPU v5": (459e12, 2.765e12),
    "TPU v5p": (459e12, 2.765e12),
    "TPU v6 lite": (918e12, 1.64e12),
    "TPU v6e": (918e12, 1.64e12),
}

# On-chip vector memory (128 MiB on every generation in TPU_PEAKS;
# make this a per-kind table if that ever diverges).  When the solve's
# whole working set fits here, the compiler keeps state resident across
# loop iterations and steady-state HBM traffic is ~0 — the byte model
# below then describes a traffic CEILING, not actual traffic, so no
# hbm_util claim is made.
TPU_VMEM_BYTES = 128 << 20


def maxsum_superstep_flops(graph: CompiledFactorGraph) -> int:
    """Arithmetic ops in one superstep (adds + mins + compares).

    Derivation per bucket of F factors, arity a, padded domain D
    (ops/maxsum.py superstep):

    - factor→var: broadcast-add a messages into the [F, D^a] table
      (a·F·D^a), then per position a min-reduction over the table
      (a·F·D^a) and a subtract (a·F·D).
    - damping on both sides: damped = d·old + (1-d)·new → 3 ops per
      element over two [F, a, D] arrays.
    - belief segment-sum: one add per message element (F·a·D) plus the
      var-cost add over [V, D].
    - var→factor: two subtracts, masked mean (sum + divide ≈ 2), and
      the normalization subtract → ≈5 ops per [F, a, D] element.
    - convergence test: |Δ|, |Σ|, two compares on both message arrays
      → ≈8 ops per element, twice.
    """
    v_plus_1, d = graph.var_costs.shape
    total = v_plus_1 * d  # belief var-cost add
    for b in graph.buckets:
        f, a = b.var_ids.shape
        table = b.costs.size  # F * D^a
        total += 2 * a * table          # broadcast adds + min reductions
        per_msg = f * a * d
        total += per_msg * (1 + 6 + 1 + 5 + 16)  # sub, damp, seg, v2f, conv
    return int(total)


def maxsum_superstep_bytes(graph: CompiledFactorGraph) -> int:
    """HBM traffic (bytes) one fused superstep must move at minimum:
    read every factor cost table once, read old + write new messages on
    both sides (4 × [F, a, D]), read/write the [V, D] belief/sum
    tables a handful of times.

    With the ell aggregation the variable-side sum reads messages
    through the padded [V+1, K] edge lists instead of one scatter
    pass: V·K message rows (padding waste included — the kernel's
    clipped dummy reads are real traffic) plus the index array
    itself, replacing one of the six message passes."""
    itemsize = graph.var_costs.dtype.itemsize
    d = graph.var_costs.shape[1]
    total = 4 * graph.var_costs.size * itemsize
    msg_passes = 6
    if graph.agg_ell is not None:
        total += graph.agg_ell.size * 4           # edge-list reads
        total += graph.agg_ell.size * d * itemsize  # padded gather
        msg_passes = 5                            # replaces one pass
    for b in graph.buckets:
        f, a = b.var_ids.shape
        total += b.costs.size * itemsize          # cost tables (read)
        total += msg_passes * f * a * d * itemsize  # v2f/f2v old+new
        total += b.var_ids.size * 4               # gather indices
    return int(total)


def working_set_bytes(graph: CompiledFactorGraph) -> int:
    """Persistent solve state: graph tensors + both message arrays and
    their suppression counters (ops/maxsum.MaxSumState)."""
    total = graph.var_costs.size * graph.var_costs.dtype.itemsize
    total += graph.var_valid.size  # bool
    if graph.agg_ell is not None:
        total += graph.agg_ell.size * 4
    d = graph.var_costs.shape[1]
    for b in graph.buckets:
        f, a = b.var_ids.shape
        total += b.costs.size * b.costs.dtype.itemsize
        total += b.var_ids.size * 4
        # v2f + f2v messages carry the var_costs dtype (ops init_state)
        total += 2 * f * a * d * graph.var_costs.dtype.itemsize
        total += 2 * f * a * 1       # send-suppression counters (int8)
    return int(total)


def roofline_report(graph: CompiledFactorGraph, cycles_per_s: float,
                    platform: str,
                    device_kind: Optional[str] = None,
                    measured: Optional[Dict[str, float]] = None,
                    ) -> Dict[str, Optional[float]]:
    """Achieved FLOP/s + utilizations for a measured superstep rate.

    ``measured`` replaces the analytical per-cycle counts with
    XLA-reported ones (observability/profiler.py): a dict with
    ``flops_per_cycle`` and/or ``bytes_per_cycle`` — each present key
    overrides its model value and the report carries
    ``cost_source='xla'``; with ``measured=None`` (or an empty dict —
    the backend-returned-nothing case) the hand model stands and
    ``cost_source='model'``.  Utilization/residency logic is identical
    either way, so a measured report stays comparable run-over-run
    with modeled ones.

    Utilization claims (mfu/hbm_util) are made only when the concrete
    chip is recognized in TPU_PEAKS; `platform == "tpu"` with an
    unknown `device_kind` reports achieved numbers with `None`
    utilizations rather than assuming some generation's peaks.

    When the whole working set fits comfortably in on-chip VMEM
    (< half TPU_VMEM_BYTES, leaving room for fusion transients), the
    compiler keeps state resident across supersteps and actual HBM
    traffic is near zero; the byte model is then only a ceiling, so
    ``hbm_util`` is None and ``vmem_resident`` is True — claiming 400%
    "HBM utilization" on a VMEM-resident problem would be nonsense.
    """
    from pydcop_tpu.ops.maxsum_lane import LaneGraph

    if isinstance(graph, LaneGraph):
        # The counters below unpack edge-major shapes positionally; a
        # lane-major graph has every axis transposed and would count
        # garbage silently (a=F in the table term, ~1e6x off).
        raise TypeError(
            "roofline_report requires the edge-major "
            "CompiledFactorGraph; convert before accounting "
            "(ops/maxsum_lane.LaneGraph shapes are transposed)")
    model_flops = maxsum_superstep_flops(graph)
    model_bytes = maxsum_superstep_bytes(graph)
    flops, bytes_moved = model_flops, model_bytes
    cost_source = "model"
    if measured:
        if measured.get("flops_per_cycle"):
            flops = float(measured["flops_per_cycle"])
            cost_source = "xla"
        if measured.get("bytes_per_cycle"):
            bytes_moved = float(measured["bytes_per_cycle"])
            cost_source = "xla"
    ws = working_set_bytes(graph)
    achieved_flops = flops * cycles_per_s
    achieved_bw = bytes_moved * cycles_per_s
    peak_flops: Optional[float] = None
    peak_bw: Optional[float] = None
    vmem_resident: Optional[bool] = None
    if platform == "tpu":
        # VMEM capacity is kind-independent (see TPU_VMEM_BYTES), so
        # residency — and the achieved_gbps suppression it implies —
        # applies to ANY TPU; only the peak-based utilization claims
        # need a recognized generation.
        vmem_resident = ws < TPU_VMEM_BYTES // 2
        if device_kind in TPU_PEAKS:
            peak_flops, peak_bw = TPU_PEAKS[device_kind]
    out = {
        "cost_source": cost_source,
        "flops_per_cycle": float(flops),
        "bytes_per_cycle": float(bytes_moved),
        "working_set_bytes": float(ws),
        "vmem_resident": vmem_resident,
        "achieved_gflops": round(achieved_flops / 1e9, 3),
        "achieved_gbps": (
            None if vmem_resident else round(achieved_bw / 1e9, 3)
        ),
        # Not rounded: on small graphs these are ~1e-9 and rounding
        # would collapse an honest tiny number to a dishonest zero.
        "mfu": (
            achieved_flops / peak_flops if peak_flops else None
        ),
        "hbm_util": (
            achieved_bw / peak_bw
            if peak_bw and vmem_resident is False else None
        ),
        # Physics gate: modeled traffic x measured rate above the
        # chip's HBM peak means the RATE is wrong (round 5: the axon
        # tunnel's block_until_ready is a partial sync, so a naive
        # wall-clock measured enqueue time and claimed 10x peak).  The
        # flag makes such a line self-refuting instead of impressive.
        "hbm_util_exceeds_peak": (
            achieved_bw > peak_bw
            if peak_bw and vmem_resident is False else None
        ),
    }
    if cost_source == "xla":
        # Keep the hand model alongside the measurement: the delta
        # between them is itself a finding (a fused chain the model
        # double-counts, or traffic XLA materializes that the model
        # assumed fused away).
        out["model_flops_per_cycle"] = float(model_flops)
        out["model_bytes_per_cycle"] = float(model_bytes)
    return out

"""Replication + repair tests.

Mirrors the reference test strategy for resilience
(tests/unit/test_reparation.py, test_reparation_removal.py): pure
builders tested in-memory, plus an end-to-end threaded run exercising
replication, agent removal and repair.
"""

import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.replication.objects import ReplicaDistribution
from pydcop_tpu.replication.path_utils import (
    add_path,
    affordable_path_from,
    before_last,
    cheapest_path_to,
    filter_missing_agents_paths,
    head,
    last,
    remove_path,
)
from pydcop_tpu.reparation import (
    create_agent_capacity_constraint,
    create_agent_hosting_constraint,
    create_computation_hosted_constraint,
    create_binary_variables_for,
)
from pydcop_tpu.reparation.removal import (
    candidate_agents,
    orphaned_computations,
    removal_info,
    unrepairable_computations,
)


class TestPathUtils:
    def test_head_last(self):
        assert head(("a", "b", "c")) == "a"
        assert last(("a", "b", "c")) == "c"
        assert before_last(("a", "b", "c")) == "b"
        assert head(()) is None
        with pytest.raises(IndexError):
            before_last(("a",))

    def test_table_sorted_insert(self):
        t = add_path([], 2.0, ("a", "b"))
        t = add_path(t, 1.0, ("a", "c"))
        assert t[0] == (1.0, ("a", "c"))

    def test_cheapest_path_to(self):
        t = [(1.0, ("a", "c")), (2.0, ("a", "b", "c")), (3.0, ("a", "b"))]
        cost, path = cheapest_path_to("c", t)
        assert cost == 1.0 and path == ("a", "c")
        cost, path = cheapest_path_to("z", t)
        assert cost == float("inf") and path == ()

    def test_affordable_path_from(self):
        t = [
            (1.0, ("a", "b")),
            (2.0, ("a", "b", "c")),
            (5.0, ("a", "b", "d")),
            (2.0, ("a", "x")),
        ]
        found = affordable_path_from(("a", "b"), 3.0, t)
        assert found == [(2.0, ("a", "b", "c"))]

    def test_filter_missing(self):
        t = [(1.0, ("a", "b")), (2.0, ("a", "c", "d"))]
        kept = filter_missing_agents_paths(t, {"b", "d"})
        assert kept == [(1.0, ("a", "b"))]

    def test_remove_path(self):
        t = [(1.0, ("a", "b")), (2.0, ("a", "c"))]
        assert remove_path(t, ("a", "b")) == [(2.0, ("a", "c"))]


class TestReplicaDistribution:
    def test_mapping(self):
        rd = ReplicaDistribution({"c1": ["a1", "a2"], "c2": ["a2"]})
        assert rd.agents_for_computation("c1") == ["a1", "a2"]
        assert rd.replicas_on("a2") == ["c1", "c2"]
        assert rd.replicas_on("a1") == ["c1"]

    def test_add_remove(self):
        rd = ReplicaDistribution({"c1": ["a1"]})
        rd.add_replica("c1", "a3")
        rd.add_replica("c1", "a3")  # idempotent
        assert rd.agents_for_computation("c1") == ["a1", "a3"]
        rd.remove_agent("a1")
        assert rd.agents_for_computation("c1") == ["a3"]


class TestReparationBuilders:
    def _vars(self):
        return create_binary_variables_for(
            ["c1", "c2"], {"c1": ["a1", "a2"], "c2": ["a2"]}
        )

    def test_binary_variables(self):
        variables = self._vars()
        assert set(variables) == {("c1", "a1"), ("c1", "a2"),
                                  ("c2", "a2")}
        assert variables[("c1", "a1")].name == "x_c1_a1"

    def test_hosted_constraint(self):
        variables = self._vars()
        c = create_computation_hosted_constraint(
            "c1", [variables[("c1", "a1")], variables[("c1", "a2")]]
        )
        assert c(0, 1) == 0
        assert c(1, 0) == 0
        assert c(1, 1) >= 10_000
        assert c(0, 0) >= 10_000

    def test_capacity_constraint(self):
        variables = self._vars()
        agt_vars = {"c1": variables[("c1", "a2")],
                    "c2": variables[("c2", "a2")]}
        c = create_agent_capacity_constraint(
            "a2", 10.0, {"c1": 6.0, "c2": 7.0}, agt_vars
        )
        # order of args follows sorted comp names: c1, c2
        assert c(1, 0) == 0
        assert c(0, 1) == 0
        assert c(1, 1) >= 10_000

    def test_hosting_constraint(self):
        variables = self._vars()
        agt_vars = {"c1": variables[("c1", "a2")],
                    "c2": variables[("c2", "a2")]}
        c = create_agent_hosting_constraint(
            "a2", {"c1": 3.0, "c2": 5.0}, agt_vars
        )
        assert c(1, 1) == 8.0
        assert c(1, 0) == 3.0
        assert c(0, 0) == 0.0


class TestRemoval:
    def test_orphaned(self):
        dist = Distribution({"a1": ["c1", "c2"], "a2": ["c3"]})
        assert orphaned_computations(["a1"], dist) == ["c1", "c2"]

    def test_candidates_exclude_departed(self):
        replicas = ReplicaDistribution(
            {"c1": ["a2", "a3"], "c2": ["a1", "a3"]}
        )
        cands = candidate_agents(["c1", "c2"], replicas, ["a1", "a2"])
        assert cands == {"c1": ["a3"], "c2": ["a3"]}

    def test_unrepairable(self):
        cands = {"c1": ["a3"], "c2": []}
        assert unrepairable_computations(cands) == ["c2"]

    def test_removal_info(self):
        dist = Distribution({"a1": ["c1"], "a2": ["c2"]})
        replicas = ReplicaDistribution({"c1": ["a2"]})
        orphaned, cands, lost = removal_info(["a1"], dist, replicas)
        assert orphaned == ["c1"]
        assert cands == {"c1": ["a2"]}
        assert lost == []


def _coloring_dcop(n_agents=4):
    """3-variable coloring over n agents with capacity + costs."""
    d = Domain("colors", "", ["R", "G", "B"])
    dcop = DCOP("resilient", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(3)]
    for v in variables:
        dcop.add_variable(v)
    for i, j in [(0, 1), (1, 2)]:
        dcop.add_constraint(constraint_from_str(
            f"diff_{i}_{j}",
            f"10 if v{i} == v{j} else 0",
            [variables[i], variables[j]],
        ))
    dcop.add_agents([
        AgentDef(f"a{i}", capacity=100, default_hosting_cost=i)
        for i in range(n_agents)
    ])
    return dcop


class TestReplicationEndToEnd:
    def _setup(self, k=2):
        from pydcop_tpu.infrastructure.run import run_local_thread_dcop

        dcop = _coloring_dcop()
        algo = AlgorithmDef.build_with_default_param("dsa", mode="min")
        cg = chg.build_computation_graph(dcop)
        # v0,v1 on a0; v2 on a1; a2/a3 idle but resilient.
        dist = Distribution(
            {"a0": ["v0", "v1"], "a1": ["v2"], "a2": [], "a3": []}
        )
        orchestrator = run_local_thread_dcop(
            algo, cg, dist, dcop, replication=True
        )
        return orchestrator

    def test_replication_places_k_replicas(self):
        orchestrator = self._setup()
        try:
            assert orchestrator.wait_ready(10)
            orchestrator.deploy_computations()
            rd = orchestrator.start_replication(2, timeout=20)
            for comp in ["v0", "v1", "v2"]:
                hosts = rd.agents_for_computation(comp)
                assert len(hosts) == 2, f"{comp}: {hosts}"
                owner = orchestrator.distribution.agent_for(comp)
                assert owner not in hosts
                assert len(set(hosts)) == 2
        finally:
            orchestrator.stop_agents(5)
            orchestrator.stop()

    def test_add_agent_then_removal(self):
        """Scenario flow: a new agent joins, replication heals onto it,
        then a departure is repaired."""
        from pydcop_tpu.dcop.scenario import (
            DcopEvent,
            EventAction,
            Scenario,
        )
        from pydcop_tpu.infrastructure.events_handler import (
            run_scenario_events,
        )

        orchestrator = self._setup()
        try:
            assert orchestrator.wait_ready(10)
            orchestrator.deploy_computations()
            orchestrator.start_replication(2, timeout=20)
            scenario = Scenario([
                DcopEvent("e_add", actions=[
                    EventAction("add_agent", agent="a9", capacity=100),
                ]),
                DcopEvent("e_rm", actions=[
                    EventAction("remove_agent", agent="a0"),
                ]),
            ])
            run_scenario_events(orchestrator, scenario)
            dist = orchestrator.distribution
            assert "a9" in dist.agents
            assert "a0" not in dist.agents
            for comp in ["v0", "v1"]:
                assert dist.agent_for(comp) != "a0"
            # Replication healed: every computation has k=2 *live*
            # replica hosts again despite a0's departure.
            live = set(dist.agents)
            for comp, hosts in orchestrator.mgt.replica_hosts.items():
                assert "a0" not in hosts
                assert len(hosts) == 2, f"{comp}: {hosts}"
                assert set(hosts) <= live
        finally:
            orchestrator.stop_agents(5)
            orchestrator.stop()

    def test_repair_after_removal(self):
        orchestrator = self._setup()
        try:
            assert orchestrator.wait_ready(10)
            orchestrator.deploy_computations()
            orchestrator.start_replication(2, timeout=20)
            placement = None
            orchestrator.pause_agents()
            orchestrator.remove_agent("a0")
            orchestrator.resume_agents()
            # v0 and v1 must have been re-hosted on live agents.
            dist = orchestrator.distribution
            assert "a0" not in dist.agents
            for comp in ["v0", "v1"]:
                host = dist.agent_for(comp)
                assert host in {"a1", "a2", "a3"}
            assert set(orchestrator.mgt.repaired_computations) == \
                {"v0", "v1"}
        finally:
            orchestrator.stop_agents(5)
            orchestrator.stop()


class _StubAgent:
    def __init__(self, name):
        self.name = name
        self.computations = []
        self.agent_def = None


class TestPlaceAnswerGuards:
    """Stale / duplicate place answers must not corrupt UCS state
    (late answers from a previous round, HTTP duplicate delivery)."""

    def _search_awaiting_place(self):
        from pydcop_tpu.replication.dist_ucs_hostingcosts import (
            HOSTING,
            UCSReplication,
            _Search,
        )

        comp = UCSReplication(_StubAgent("a0"), discovery=None)
        comp._msg_sender = lambda *a, **kw: None
        search = _Search("v0", None, 1.0, k=2, origin="a0")
        path = ("a0", "a1", HOSTING)
        search.awaiting = ("place", path, 3.0)
        comp._searches = {"v0": search}
        return comp, search, path

    def test_stale_path_ignored(self):
        from pydcop_tpu.replication.dist_ucs_hostingcosts import (
            HOSTING,
            PlaceReplicaAnswerMessage,
        )

        comp, search, _ = self._search_awaiting_place()
        stale = PlaceReplicaAnswerMessage(
            "v0", True, ("a0", "a2", HOSTING)
        )
        comp._on_place_answer("_replication_a2", stale, 0.0)
        assert search.awaiting is not None
        assert search.k_remaining == 2
        assert search.hosts == []

    def test_probe_answer_does_not_clear_place_wait(self):
        from pydcop_tpu.replication.dist_ucs_hostingcosts import (
            UCSProbeAnswerMessage,
        )

        comp, search, _ = self._search_awaiting_place()
        probe_ans = UCSProbeAnswerMessage(
            "v0", ("a0", "a1"), True, 1.0, {}
        )
        comp._on_probe_answer("_replication_a1", probe_ans, 0.0)
        assert search.awaiting is not None
        assert search.frontier == []

    def test_duplicate_accept_decrements_once(self):
        from pydcop_tpu.replication.dist_ucs_hostingcosts import (
            PlaceReplicaAnswerMessage,
        )

        comp, search, path = self._search_awaiting_place()
        answer = PlaceReplicaAnswerMessage("v0", True, path)
        comp._on_place_answer("_replication_a1", answer, 0.0)
        assert search.hosts == ["a1"]
        assert search.k_remaining == 1
        # Duplicate delivery (e.g. HTTP retry after a timed-out but
        # processed POST): awaiting was cleared, so it is a no-op.
        comp._on_place_answer("_replication_a1", answer, 0.0)
        assert search.hosts == ["a1"]
        assert search.k_remaining == 1


class TestHttpRetryPurge:
    def test_departed_agent_traffic_purged_and_dropped(self):
        from pydcop_tpu.infrastructure.communication import (
            ComputationMessage,
            HttpCommunicationLayer,
            MSG_ALGO,
        )
        from pydcop_tpu.infrastructure.computations import Message
        from pydcop_tpu.infrastructure.discovery import Discovery

        layer = HttpCommunicationLayer(("127.0.0.1", 0))
        try:
            # Port 0 picks an ephemeral port for our own server; the
            # peer address is unreachable on purpose.
            discovery = Discovery("me", ("127.0.0.1", 1))
            discovery.agent_change_hooks.append(layer.on_agent_change)
            layer.discovery = discovery
            discovery.register_agent(
                "peer", ("127.0.0.1", 1), publish=False
            )
            cmsg = ComputationMessage(
                "c1", "c2", Message("test", None), MSG_ALGO
            )
            layer.send_msg("me", "peer", cmsg)
            assert len(layer._retry_queue) == 1
            discovery.unregister_agent("peer", publish=False)
            assert layer._retry_queue == []
            # New sends to the departed agent are dropped outright.
            layer.send_msg("me", "peer", cmsg)
            assert layer._retry_queue == []
            # Re-added under the same name: traffic flows (and fails
            # into the retry queue) again.
            discovery.register_agent(
                "peer", ("127.0.0.1", 1), publish=False
            )
            layer.send_msg("me", "peer", cmsg)
            assert len(layer._retry_queue) == 1
        finally:
            layer.shutdown()


class TestRepairGreedyFallback:
    """When the device solve of the repair DCOP is unavailable, repair
    must fall back to the greedy capacity-aware placement (VERDICT #8
    "repair fallback path" untested; orchestrator
    _assign_from_repair_solve)."""

    def test_repair_succeeds_when_device_solve_fails(self, monkeypatch):
        from pydcop_tpu.infrastructure.run import run_local_thread_dcop
        import pydcop_tpu.api as api

        def boom(*args, **kwargs):
            raise RuntimeError("device backend unavailable")

        monkeypatch.setattr(api, "solve", boom)

        dcop = _coloring_dcop()
        algo = AlgorithmDef.build_with_default_param("dsa", mode="min")
        cg = chg.build_computation_graph(dcop)
        dist = Distribution(
            {"a0": ["v0", "v1"], "a1": ["v2"], "a2": [], "a3": []}
        )
        orchestrator = run_local_thread_dcop(
            algo, cg, dist, dcop, replication=True
        )
        try:
            assert orchestrator.wait_ready(10)
            orchestrator.deploy_computations()
            orchestrator.start_replication(2, timeout=20)
            orchestrator.pause_agents()
            orchestrator.remove_agent("a0")
            orchestrator.resume_agents()
            new_dist = orchestrator.distribution
            # The greedy fallback deterministically prefers the
            # cheapest hosting cost with capacity: a1 (cost 1) beats
            # a2 (2) and a3 (3) and has room for both orphans — an
            # assignment signature the (approximate, comm-cost-aware)
            # device solve would not reliably produce, proving the
            # fallback path actually ran.
            for comp in ["v0", "v1"]:
                assert new_dist.agent_for(comp) == "a1"
            assert set(orchestrator.mgt.repaired_computations) == \
                {"v0", "v1"}
        finally:
            orchestrator.stop_agents(5)
            orchestrator.stop()


class TestDistributedRepair:
    """VERDICT missing #5: the repair DCOP solved *among candidate
    agents* (repair computations deployed on the candidates, bounded
    synchronous search, values collected) instead of centrally."""

    def _setup(self):
        from pydcop_tpu.infrastructure.run import run_local_thread_dcop

        dcop = _coloring_dcop()
        algo = AlgorithmDef.build_with_default_param("dsa", mode="min")
        cg = chg.build_computation_graph(dcop)
        dist = Distribution(
            {"a0": ["v0", "v1"], "a1": ["v2"], "a2": [], "a3": []}
        )
        return run_local_thread_dcop(
            algo, cg, dist, dcop, replication=True,
            repair_mode="distributed",
        )

    def test_repair_runs_on_candidate_agents(self):
        orchestrator = self._setup()
        try:
            assert orchestrator.wait_ready(10)
            orchestrator.deploy_computations()
            orchestrator.start_replication(2, timeout=20)
            orchestrator.pause_agents()
            orchestrator.remove_agent("a0")
            orchestrator.resume_agents()
            dist = orchestrator.distribution
            for comp in ["v0", "v1"]:
                assert dist.agent_for(comp) in {"a1", "a2", "a3"}
            assert set(orchestrator.mgt.repaired_computations) == \
                {"v0", "v1"}
            # The temporary repair computations were retired: no x_*
            # computations remain in the collected assignment, and no
            # agent still hosts one.
            assert not any(
                k.startswith("x_") for k in orchestrator.mgt.assignment
            )
            assert not any(
                k.startswith("x_")
                for k in orchestrator.mgt.finished_computations
            )
        finally:
            orchestrator.stop_agents(5)
            orchestrator.stop()

"""Random graph structure generators (edge lists, no networkx).

Used by the problem generators: Erdős-Rényi random graphs (with a
single-component guarantee by default), 2-D grids (optionally toroidal),
Barabási-Albert scale-free graphs and Watts-Strogatz small worlds.
Reference analogues: pydcop/commands/generators/graphcoloring.py:310-354
(which delegate to networkx).
"""

from typing import List, Optional, Set, Tuple

import numpy as np

Edge = Tuple[int, int]


def _connect_components(n: int, edges: Set[Edge],
                        rng: np.random.Generator) -> Set[Edge]:
    """Add random edges until the graph has a single component."""
    parent = list(range(n))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    for a, b in edges:
        union(a, b)
    roots = {find(i) for i in range(n)}
    while len(roots) > 1:
        comps = {}
        for i in range(n):
            comps.setdefault(find(i), []).append(i)
        groups = list(comps.values())
        a = groups[0][rng.integers(len(groups[0]))]
        b = groups[1][rng.integers(len(groups[1]))]
        edges.add((min(a, b), max(a, b)))
        union(a, b)
        roots = {find(i) for i in range(n)}
    return edges


def random_graph(n: int, p_edge: float, allow_subgraph: bool = False,
                 seed: Optional[int] = None) -> List[Edge]:
    """Erdős-Rényi G(n, p); connected unless allow_subgraph."""
    rng = np.random.default_rng(seed)
    edges = set()
    # Row-wise sampling keeps memory at O(n) instead of a dense n x n
    # matrix (matters for benchmark-scale graphs).
    for i in range(n - 1):
        row = rng.random(n - i - 1) < p_edge
        for off in np.nonzero(row)[0]:
            edges.add((i, i + 1 + int(off)))
    if not allow_subgraph:
        edges = _connect_components(n, edges, rng)
    return sorted(edges)


def grid_graph(n: int, periodic: bool = False) -> List[Edge]:
    """Square 2-D grid over the first s*s >= n nodes (reference uses
    exact squares; callers should pass a square count)."""
    side = int(np.sqrt(n))
    if side * side != n:
        raise ValueError(
            f"Grid graphs require a square variable count, got {n}"
        )
    edges = set()
    for r in range(side):
        for c in range(side):
            i = r * side + c
            if c + 1 < side:
                edges.add((i, r * side + c + 1))
            elif periodic and side > 2:
                edges.add((min(i, r * side), max(i, r * side)))
            if r + 1 < side:
                edges.add((i, (r + 1) * side + c))
            elif periodic and side > 2:
                edges.add((min(i, c), max(i, c)))
    return sorted(edges)


def grid_2d_graph(rows: int, cols: int,
                  periodic: bool = True) -> List[Tuple]:
    """Grid over (row, col) nodes, toroidal by default (ising layout,
    reference ising.py:285 nx.grid_2d_graph periodic)."""
    edges = set()
    for r in range(rows):
        for c in range(cols):
            right = (r, (c + 1) % cols) if periodic else (
                (r, c + 1) if c + 1 < cols else None
            )
            down = ((r + 1) % rows, c) if periodic else (
                (r + 1, c) if r + 1 < rows else None
            )
            for other in (right, down):
                if other is not None and other != (r, c):
                    edges.add(tuple(sorted([(r, c), other])))
    return sorted(edges)


def scalefree_graph(n: int, m_edge: int, allow_subgraph: bool = False,
                    seed: Optional[int] = None) -> List[Edge]:
    """Barabási-Albert preferential attachment: each new node attaches
    to m existing nodes with probability proportional to degree."""
    if m_edge < 1 or m_edge >= n:
        raise ValueError("scalefree requires 1 <= m_edge < n")
    rng = np.random.default_rng(seed)
    edges: Set[Edge] = set()
    targets = list(range(m_edge))
    repeated: List[int] = []
    for new in range(m_edge, n):
        for t in set(targets):
            edges.add((min(new, t), max(new, t)))
        repeated.extend(set(targets))
        repeated.extend([new] * m_edge)
        # Sample next targets by degree (nodes repeated by degree).
        targets = [
            repeated[rng.integers(len(repeated))] for _ in range(m_edge)
        ]
    if not allow_subgraph:
        edges = _connect_components(n, edges, rng)
    return sorted(edges)


def small_world_graph(n: int, k: int = 4, p_rewire: float = 0.1,
                      seed: Optional[int] = None) -> List[Edge]:
    """Watts-Strogatz ring lattice with random rewiring."""
    rng = np.random.default_rng(seed)
    edges: Set[Edge] = set()
    degree = [0] * n
    for i in range(n):
        for j in range(1, k // 2 + 1):
            a, b = i, (i + j) % n
            if a == b:
                continue
            if rng.random() < p_rewire and degree[a] < n - 1:
                # Rewire; skip (keep lattice edge) if we cannot find a
                # free target quickly — avoids spinning when a is close
                # to saturated.
                for _ in range(8 * n):
                    cand = int(rng.integers(n))
                    if cand != a and (min(a, cand), max(a, cand)) \
                            not in edges:
                        b = cand
                        break
            e = (min(a, b), max(a, b))
            if e not in edges:
                edges.add(e)
                degree[a] += 1
                degree[e[0] if e[1] == a else e[1]] += 1
    return sorted(edges)

"""SyncBB: Synchronous Branch & Bound — complete search over a total
variable order.

Reference parity: pydcop/algorithms/syncbb.py (:160-512): variables in
lexical order exchange forward (partial path + bound) / backward /
terminate messages, one token in flight; each step extends the path with
the next value whose partial cost stays under the current bound.

Engine path: the same search executed as an iterative host DFS over the
ordered graph — sequential by nature (one token in the reference too),
so there is nothing to batch; constraint tables are pre-materialized
dense so per-step evaluation is array indexing, and partial costs are
accumulated incrementally per depth (a constraint is charged at the
depth where its last scope variable is assigned).

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'syncbb')
    >>> round(res['cost'], 3)
    0.0
"""

from typing import Dict, List, Optional

import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.computations_graph import ordered_graph as og
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.runner import DeviceRunResult

GRAPH_TYPE = "ordered_graph"

algo_params = []


def computation_memory(node) -> float:
    return og.computation_memory(node)


def communication_load(src, target: str) -> float:
    return og.communication_load(src, target)


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("syncbb", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 0, mesh=None,
                    n_devices: Optional[int] = None,
                    **_) -> DeviceRunResult:
    import time

    t0 = time.perf_counter()
    mode = dcop.objective
    sign = 1.0 if mode == "min" else -1.0
    variables = sorted(dcop.variables.values(), key=lambda v: v.name)
    var_index = {v.name: i for i, v in enumerate(variables)}
    domains = [list(v.domain) for v in variables]

    # Unary costs per variable (sign-adjusted so we always minimize).
    unary = [sign * v.cost_vector() for v in variables]

    # Charge each constraint at the depth where its scope completes.
    charged: List[List] = [[] for _ in variables]
    for c in dcop.constraints.values():
        if c.arity == 0:
            continue
        positions = [var_index[n] for n in c.scope_names]
        table = sign * np.asarray(c.to_array(), dtype=np.float64)
        charged[max(positions)].append((positions, table))

    n = len(variables)
    # Admissible future bound per depth: the best the not-yet-charged
    # costs could still contribute (needed for pruning correctness when
    # costs are negative, e.g. negated max-mode tables).
    step_lb = [
        float(np.min(unary[d])) + sum(
            float(np.min(table)) for _, table in charged[d]
        )
        for d in range(n)
    ]
    future_lb = [0.0] * (n + 1)
    for d in range(n - 1, -1, -1):
        future_lb[d] = future_lb[d + 1] + step_lb[d]

    best_cost = np.inf
    best_assignment: Optional[List[int]] = None
    # DFS stack: current value index per depth, -1 = not yet branched.
    values = [-1] * n
    prefix_cost = [0.0] * (n + 1)
    depth = 0
    steps = 0
    while depth >= 0:
        values[depth] += 1
        if values[depth] >= len(domains[depth]):
            values[depth] = -1
            depth -= 1
            continue
        steps += 1
        cost = prefix_cost[depth] + unary[depth][values[depth]]
        for positions, table in charged[depth]:
            cost += table[tuple(values[p] for p in positions)]
        if cost + future_lb[depth + 1] >= best_cost:
            continue  # prune: even a perfect completion cannot improve
        if depth == n - 1:
            best_cost = cost
            best_assignment = values[:]
            continue
        prefix_cost[depth + 1] = cost
        depth += 1

    elapsed = time.perf_counter() - t0
    if best_assignment is None:
        # Every full assignment hit an infinite cost: report initial.
        assignment = dcop.initial_assignment()
    else:
        assignment = {
            v.name: domains[i][best_assignment[i]]
            for i, v in enumerate(variables)
        }
    cost, _ = dcop.solution_cost(assignment)
    return DeviceRunResult(
        assignment=assignment,
        cycles=steps,
        converged=True,
        time_s=elapsed,
        compile_time_s=0.0,
        metrics={"msg_count": steps, "device_cost": cost},
    )

"""``pydcop consolidate``: extract statistics from result files.

Reference parity: pydcop/commands/consolidate.py — two modes:

- ``--solution``: extract end metrics (time, cost, cycle, msg_count,
  msg_size, status) from JSON result files into CSV rows;
- ``--distribution_cost <dist glob>``: evaluate distribution files
  against a DCOP (cost / hosting / communication, using the
  ilp_compref cost model).
"""

import csv
import glob
import io
import json
import logging
import os

logger = logging.getLogger("pydcop.cli.consolidate")

SOLUTION_HEADER = ["time", "cost", "cycle", "msg_count", "msg_size",
                   "status"]
DIST_HEADER = ["dcop", "distribution", "cost", "hosting",
               "communication"]


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "consolidate", help="consolidate result files into csv")
    parser.add_argument("files", nargs="+", help="input file(s)")
    parser.add_argument("--solution", action="store_true", default=False,
                        help="extract end metrics from json results")
    parser.add_argument("--distribution_cost", default=None,
                        help="distribution file (or glob) to cost "
                             "against the dcop given in files")
    parser.add_argument("--algo", default=None,
                        help="algorithm (for distribution costs)")
    parser.add_argument("--average", action="store_true", default=False,
                        help="average end metrics over the given json "
                             "result files (the reference declares "
                             "this flag but never implemented it; "
                             "here it works)")
    parser.add_argument("--replace_output", action="store_true",
                        default=False,
                        help="overwrite the output file instead of "
                             "appending")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    if args.output and args.replace_output and \
            os.path.exists(args.output):
        os.remove(args.output)
    if args.solution:
        rows = []
        for f in args.files:
            try:
                rows.append(_solution_row(f))
            except Exception as e:
                logger.warning("Skipping %s: %s", f, e)
        _emit(rows, SOLUTION_HEADER, args.output)
        return 0
    if args.distribution_cost:
        rows = _distribution_rows(
            args.files, args.distribution_cost, args.algo
        )
        _emit(rows, DIST_HEADER, args.output)
        return 0
    if args.average:
        row, count = _average_row(args.files)
        if not count:
            print("Error: no parseable result file among "
                  f"{args.files}")
            return 2
        _emit([row], ["n_runs"] + SOLUTION_HEADER[:-1] +
              ["finished_frac"], args.output)
        return 0
    print("Error: choose --solution, --distribution_cost or --average")
    return 2


def _solution_row(path: str):
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return [data.get(k) for k in SOLUTION_HEADER]


def _average_row(files):
    """Mean of the numeric end metrics over result files + the
    fraction of runs that FINISHED; non-parsable files are skipped
    with a warning (matching --solution)."""
    numeric = SOLUTION_HEADER[:-1]  # all but status
    sums = {k: 0.0 for k in numeric}
    counts = {k: 0 for k in numeric}
    finished = 0
    n = 0
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except Exception as e:
            logger.warning("Skipping %s: %s", path, e)
            continue
        n += 1
        if data.get("status") == "FINISHED":
            finished += 1
        for k in numeric:
            v = data.get(k)
            if isinstance(v, (int, float)):
                sums[k] += v
                counts[k] += 1
    row = [n] + [
        round(sums[k] / counts[k], 6) if counts[k] else None
        for k in numeric
    ] + [round(finished / n, 4) if n else None]
    return row, n


def _distribution_rows(dcop_files, dist_glob, algo):
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file
    from pydcop_tpu.distribution import ilp_compref
    from pydcop_tpu.distribution.yamlformat import load_dist_from_file

    dcop = load_dcop_from_file(dcop_files)
    algo_module = load_algorithm_module(algo)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    rows = []
    for dist_file in sorted(
        glob.glob(os.path.expanduser(dist_glob))
    ):
        try:
            distribution = load_dist_from_file(dist_file)
            cost, comm, hosting = ilp_compref.distribution_cost(
                distribution, cg, dcop.agents.values(),
                computation_memory=algo_module.computation_memory,
                communication_load=algo_module.communication_load,
            )
            rows.append(
                [dcop_files[0], dist_file, cost, hosting, comm]
            )
        except Exception as e:
            logger.warning("Skipping %s: %s", dist_file, e)
    return rows


def _emit(rows, header, output):
    if output:
        new_file = not os.path.exists(output)
        with open(output, "a", newline="", encoding="utf-8") as f:
            writer = csv.writer(f)
            if new_file:
                writer.writerow(header)
            writer.writerows(rows)
    else:
        buffer = io.StringIO()
        writer = csv.writer(buffer)
        writer.writerows(rows)
        print(buffer.getvalue(), end="")

"""MaxSum message-update kernels: one BSP superstep as pure JAX.

Semantics mirror the reference algorithm exactly (factor update:
pydcop/algorithms/maxsum.py:382 factor_costs_for_var; variable update:
:623 costs_for_factor with mean-normalization :670-674; damping :679;
convergence test :688 approx_match), but batched:

- factor→variable: per arity-bucket, ``total = costs + Σ_q bcast(m_q)``
  then for each position p ``min`` over all axes except p minus ``m_p``
  (m_p is constant along the reduced axes, so subtracting it after the
  reduction equals excluding it before) — one batched reduction instead
  of a python loop over d^arity assignments;
- variable→factor: segment-sum of incoming messages over the bucket var
  indices, per-slot "subtract own contribution", mean-normalized over
  valid domain slots, damped;
- value selection: argmin of (own costs + message sums) masked to valid
  slots; argmin's lowest-index tie-break reproduces the reference's
  first-optimum ordering (maxsum.py:584 select_value iterates the domain
  in order).

Messages live in bucket space ([F, arity, D] per bucket): factor updates
touch only local rows, and the single segment-sum is the only op that
crosses shards when buckets are sharded over a mesh (one all-reduce of
the [V+1, D] totals per superstep).

All kernels minimize; `objective=max` problems are negated at compile
time (see engine.compile).

Pallas note: a hand-written Pallas kernel for the binary-factor update
(blocking F onto lanes, one fused min-reduce pass) was prototyped and
measured on a v5e chip at parity with XLA's fusion of this code
(~0.26-0.34 ms/superstep on the 15k-factor benchmark, both ways) —
the op mix here is gather/scatter + tiny-minor-dim elementwise, which
Mosaic cannot schedule better than XLA does.  The XLA path is kept;
revisit Pallas if a future problem shape makes the factor update
reduction-bound (large arity/domains) rather than dispatch-bound.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import (
    BIG,
    PRUNE_MIN_DOMAIN,
    CompiledFactorGraph,
    prune_width,
)

Msgs = Tuple[jnp.ndarray, ...]  # one [F, arity, D] array per bucket

# Reference maxsum.py:106 SAME_COUNT: a message that approx-matches the
# previously sent one is re-sent at most this many times, then the edge
# goes quiet (the receiver keeps the last value).
SAME_COUNT = 4


class MaxSumState(NamedTuple):
    v2f: Msgs            # last SENT variable -> factor messages
    f2v: Msgs            # last SENT factor -> variable messages
    v2f_count: Msgs      # [F, arity] int8 consecutive-same send counts
    f2v_count: Msgs
    stable: jnp.ndarray  # scalar bool: all messages approx-matched
    cycle: jnp.ndarray   # scalar int32


def init_state(graph: CompiledFactorGraph) -> MaxSumState:
    d = graph.var_costs.shape[1]
    dtype = graph.var_costs.dtype

    # int8 counts: they saturate at SAME_COUNT + 1 = 5, and the two
    # counter arrays are read+written every cycle — int32 would
    # spend 4x the HBM traffic on values that never exceed 5.
    # Each field gets its OWN arrays (no tuple reuse across v2f/f2v):
    # the segment jits donate the state pytree (engine/runner.py), and
    # donation rejects the same buffer appearing in two donated slots.
    def zeros():
        return tuple(
            jnp.zeros(b.var_ids.shape + (d,), dtype=dtype)
            for b in graph.buckets
        )

    def counts():
        return tuple(
            jnp.zeros(b.var_ids.shape, dtype=jnp.int8)
            for b in graph.buckets
        )

    return MaxSumState(
        v2f=zeros(),
        f2v=zeros(),
        v2f_count=counts(),
        f2v_count=counts(),
        stable=jnp.asarray(False),
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _edge_match(new: jnp.ndarray, old: jnp.ndarray, stability: float,
                valid: jnp.ndarray) -> jnp.ndarray:
    """Per-edge reference approx_match (maxsum.py:688): relative change
    2|Δ|/|a+b| below `stability` on every domain slot (exact equality
    always matches).  Slots outside `valid` (domain padding, sentinel
    padding rows) are ignored so device padding cannot delay
    convergence.  Returns [F, arity] bool."""
    delta = jnp.abs(new - old)
    s = jnp.abs(new + old)
    # Algebraically identical to the reference's three-case test
    # (delta==0 → True; s==0 → False; else 2·delta/s < stability) with
    # two fewer ops per element: when delta>0 and s==0 the strict
    # comparison 0 < 0 is already False, and the delta==0 clause
    # restores the exact-equality case regardless of s.  Bit-identical
    # trajectories verified against the previous form at 10k vars
    # (~7% faster superstep on the CPU backend).
    ok = (2 * delta < stability * s) | (delta == 0)
    return jnp.all(ok | ~valid, axis=-1)


def _send_or_suppress(cand: jnp.ndarray, prev: jnp.ndarray,
                      count: jnp.ndarray, stability: float,
                      valid: jnp.ndarray, first: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reference send-suppression (maxsum.py:366-377 via send_damped):
    a candidate that approx-matches the last sent message is re-sent at
    most SAME_COUNT times, then the edge freezes on the last sent value
    (the thread runtime's receiver keeps its cached copy; here the
    frozen value simply stays in the state array).

    Returns (sent messages, new counts, per-edge match flags).
    """
    match = _edge_match(cand, prev, stability, valid) & ~first
    send = ~match | (count < SAME_COUNT)
    sent = jnp.where(send[..., None], cand, prev)
    new_count = jnp.where(
        match, jnp.minimum(count + 1, SAME_COUNT + 1), 1
    )
    return sent, new_count, match


def _read_pallas_flag() -> bool:
    import os

    return os.environ.get("PYDCOP_PALLAS_MAXSUM") == "1"


# Read ONCE at import (ADVICE r2): the engines' jit caches do not key on
# this flag, so a mid-process toggle would be silently ignored anyway —
# snapshotting it here makes the set-before-import contract explicit.
_PALLAS_FLAG = _read_pallas_flag()


def _use_pallas() -> bool:
    """Opt-in Pallas path for the binary-factor update (TPU only;
    PYDCOP_PALLAS_MAXSUM=1 must be set before this module is imported).
    Default off: measured at parity with XLA's fusion on v5e — see
    ops/pallas_maxsum.py for the full status."""
    return (
        _PALLAS_FLAG
        and jax.default_backend() == "tpu"
        # Sharded buckets (mesh runs) cannot feed pallas_call without
        # gathering the whole bucket per superstep — single chip only.
        and jax.device_count() == 1
    )


class PruneTable(NamedTuple):
    """Per-bucket branch-and-bound tables for the pruned binary-factor
    update (arXiv:1906.06863 applied to the min-plus aggregation).

    ``row_min``/``row_max`` hold, per factor and per slot of one scope
    position, the min/max of the cost hypercube over the *other*
    position's VALID slots — the message-independent halves of the
    per-row lower bound (``m_q[e] + row_min[e]``) and the running
    upper bound (``min_e(m_q[e] + row_max[e])``).  Both are pure
    functions of the cost tables, computed ONCE outside the jitted
    loop (never per superstep).  ``valid`` masks each position's
    domain-padding slots out of the survivor set and the upper bound.
    """

    row_min: Tuple[jnp.ndarray, jnp.ndarray]  # per position p: [F, D]
    row_max: Tuple[jnp.ndarray, jnp.ndarray]
    valid: Tuple[jnp.ndarray, jnp.ndarray]    # [F, D] bool
    costs_t: jnp.ndarray                      # [F, D, D] transposed
    width: int                                # static gather budget


def prune_tables(graph: CompiledFactorGraph
                 ) -> Tuple[Optional[PruneTable], ...]:
    """Branch-and-bound tables, one entry per bucket (None = bucket
    stays on the dense path: non-binary arity, or a domain small
    enough that the bound bookkeeping would cost more than the dense
    reduction).  Call OUTSIDE the superstep loop — the tables are
    loop-invariant."""
    out = []
    d = graph.var_costs.shape[1]
    for bucket in graph.buckets:
        if (bucket.var_ids.shape[1] != 2 or d < PRUNE_MIN_DOMAIN
                or bucket.var_ids.shape[0] == 0):
            out.append(None)
            continue
        valid0 = graph.var_valid[bucket.var_ids[:, 0]]   # [F, D]
        valid1 = graph.var_valid[bucket.var_ids[:, 1]]
        costs = bucket.costs                             # [F, D, D]
        inf = jnp.asarray(jnp.inf, costs.dtype)
        # Extrema over the VALID slots of the other position: BIG
        # domain padding must not loosen row_max into uselessness.
        m0 = valid0[:, :, None]
        m1 = valid1[:, None, :]
        out.append(PruneTable(
            row_min=(
                jnp.min(jnp.where(m1, costs, inf), axis=2),    # p=0
                jnp.min(jnp.where(m0, costs, inf), axis=1),    # p=1
            ),
            row_max=(
                jnp.max(jnp.where(m1, costs, -inf), axis=2),
                jnp.max(jnp.where(m0, costs, -inf), axis=1),
            ),
            valid=(valid0, valid1),
            # Direction p=0 gathers reduction rows indexed by the
            # q=1 slot: the transposed table makes that a CONTIGUOUS
            # row copy instead of a strided column gather (the
            # strided form measured 4x slower on XLA:CPU).  2x table
            # memory, paid only while pruning is on.
            costs_t=jnp.swapaxes(costs, 1, 2),
            width=prune_width(d),
        ))
    return tuple(out)


# Relative slack added to the survivor test: the lower/upper bounds
# and the reduction totals are DIFFERENT float computations of related
# real quantities, each off by a few ulps — an entry whose real margin
# is inside the rounding noise must survive, or the pruned min can
# differ from the dense min in the last bits.  ~200x f32 eps keeps
# every near-boundary entry (measured: zero extra survivors on the
# benchmark families, bit-identical trajectories restored at D=192
# where slack-free pruning drifted).
PRUNE_SLACK = 2.5e-5


def _survivors(msgs: jnp.ndarray, pt: PruneTable, p: int
               ) -> jnp.ndarray:
    """[F, D] bool: reduction rows of direction ``p`` that can still
    attain the min.  Row ``e`` is DOMINATED when its lower bound
    ``m_q[e] + row_min[e]`` exceeds the factor's running upper bound
    ``min_e(m_q[e] + row_max[e])`` by more than the rounding slack:
    every output entry is <= the upper bound, so removing the row is
    exact (ties and near-ties keep it)."""
    mq = msgs[:, 1 - p]
    vq = pt.valid[1 - p]
    inf = jnp.asarray(jnp.inf, mq.dtype)
    lb = mq + pt.row_min[p]
    ub = jnp.min(jnp.where(vq, mq + pt.row_max[p], inf),
                 axis=1, keepdims=True)
    tau = PRUNE_SLACK * (1.0 + jnp.abs(ub))
    return vq & (lb <= ub + tau)


def prune_fits(v2f: Msgs,
               prune: Tuple[Optional[PruneTable], ...]) -> jnp.ndarray:
    """Scalar bool: every prunable bucket's survivor count fits the
    static gather budget in BOTH directions for the messages about to
    be consumed — the phase predicate of the pruned solve loops (see
    run_maxsum_from).  O(E) bound arithmetic, no reduction hypercube
    touched."""
    fits = jnp.asarray(True)
    for msgs, pt in zip(v2f, prune):
        if pt is None:
            continue
        for p in range(2):
            n = jnp.max(jnp.sum(
                _survivors(msgs, pt, p).astype(jnp.int32), axis=1))
            fits = fits & (n <= pt.width)
    return fits


def _pruned_binary_update(bucket, msgs: jnp.ndarray,
                          pt: PruneTable) -> jnp.ndarray:
    """Branch-and-bound f2v update for one binary bucket ([F, 2, D]).

    PRECONDITION: every factor's survivor count fits ``pt.width`` in
    both directions (``prune_fits``) — the pruned solve loops only
    enter this kernel while that holds, so there is no in-kernel
    fallback.  (An XLA conditional here was measured to cost more
    than the dense reduction it avoids: conditional branch operands —
    the [F, D, D] cost tensors — don't alias across the control-flow
    boundary on CPU, so every cycle paid a hypercube-sized copy.
    While-loop phase switching keeps the big operands in the loop
    carry/closure where they DO alias.)

    Under the precondition the result is the SAME VALUE the dense
    reduction produces — dominated rows are strictly above the
    attainable min, ties survive, and the per-element add order
    matches the dense path exactly ((costs + m0) + m1, reduce,
    subtract own message) — so on integer cost tables the whole
    trajectory is bit-identical (asserted in
    tests/unit/test_workreduction_battery.py, gated in perf-smoke).

    Work shape: survivors are compacted sort-free — the j-th survivor
    index is recovered from the monotone prefix counts by an unrolled
    O(K·log D) binary search (XLA sort/scatter/top_k all measured
    20-30x slower per element on CPU) — then both directions gather
    CONTIGUOUS [K, D] row blocks (direction 0 from the pre-transposed
    table) and min-plus reduce over K instead of D.  Slots past the
    last survivor duplicate a row that is either itself a survivor or
    dominated — the gathered min stays exact either way.  The
    per-element add order matches the dense path exactly
    ((costs + m0) + m1, reduce, subtract own message): damping and
    mean-normalization accrete mantissa bits cycle over cycle, so an
    "algebraically equal" reassociation (e.g. skipping the
    add-then-subtract of the own message) measurably drifts within
    ~15 cycles even on integer tables.
    """
    costs = bucket.costs
    m0, m1 = msgs[:, 0], msgs[:, 1]
    k = pt.width
    d = costs.shape[1]
    outs = []
    for p in range(2):
        s = _survivors(msgs, pt, p)
        cum = jnp.cumsum(s.astype(jnp.int32), axis=1)       # [F, D]
        # idx[f, j] = first e with cum[e] == j+1 (the j-th survivor):
        # an unrolled branchless lower_bound over the monotone prefix
        # counts, all K targets searched at once — O(K·log D) gathers
        # instead of the O(K·D) compare-and-count matrix.
        target = jnp.arange(1, k + 1, dtype=jnp.int32)[None, :]
        idx = jnp.zeros((cum.shape[0], k), jnp.int32)
        bit = 1
        while bit * 2 <= d:
            bit <<= 1
        while bit:
            nxt = idx + bit
            probe = jnp.take_along_axis(
                cum, jnp.minimum(nxt, d) - 1, axis=1)
            idx = jnp.where((nxt <= d) & (probe < target), nxt, idx)
            bit >>= 1
        idx = jnp.minimum(idx, d - 1)
        if p == 0:
            c_g = jnp.take_along_axis(
                pt.costs_t, idx[:, :, None], axis=1)        # [F, K, D]
            m1_g = jnp.take_along_axis(m1, idx, axis=1)
            total = (c_g + m0[:, None, :]) + m1_g[:, :, None]
            outs.append(jnp.min(total, axis=1) - m0)
        else:
            c_g = jnp.take_along_axis(
                costs, idx[:, :, None], axis=1)             # [F, K, D]
            m0_g = jnp.take_along_axis(m0, idx, axis=1)
            total = (c_g + m0_g[:, :, None]) + m1[:, None, :]
            outs.append(jnp.min(total, axis=1) - m1)
    return jnp.stack(outs, axis=1)


def factor_to_var(graph: CompiledFactorGraph, v2f: Msgs,
                  prune: Optional[Tuple[Optional[PruneTable], ...]]
                  = None) -> Msgs:
    """All factor→variable messages for one superstep.  ``prune``
    (from :func:`prune_tables`) routes binary buckets through the
    branch-and-bound update — same values, less work as the messages
    concentrate."""
    out = []
    use_pallas = _use_pallas()
    for bi, (bucket, msgs) in enumerate(zip(graph.buckets, v2f)):
        if prune is not None and prune[bi] is not None:
            out.append(_pruned_binary_update(bucket, msgs, prune[bi]))
            continue
        if use_pallas and bucket.var_ids.shape[1] == 2:
            from pydcop_tpu.ops.pallas_maxsum import (
                binary_factor_update,
            )

            out.append(binary_factor_update(bucket.costs, msgs))
            continue
        f, arity, d = msgs.shape
        total = bucket.costs  # [F, D, ..., D]
        for q in range(arity):
            shape = [f] + [1] * arity
            shape[q + 1] = d
            total = total + msgs[:, q].reshape(shape)
        outs_p = []
        for p in range(arity):
            axes = tuple(i + 1 for i in range(arity) if i != p)
            reduced = jnp.min(total, axis=axes) if axes else total
            outs_p.append(reduced - msgs[:, p])
        out.append(jnp.stack(outs_p, axis=1))  # [F, arity, D]
    return tuple(out)


def aggregate_beliefs(graph: CompiledFactorGraph, f2v: Msgs
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sum incoming factor messages per variable.

    Returns (beliefs [V+1, D] = own costs + sums, sums [V+1, D]).
    This aggregation is the single cross-shard op per superstep, and
    the op that dominates past the 100k-var scale cliff (BENCH_TPU.md).
    Strategy is chosen at compile time via the graph's ``agg_*`` arrays
    (engine/compile.build_aggregation_arrays; A/B harness
    benchmarks/exp_aggregation.py):

    - default: unsorted scatter-add, one ``segment_sum`` per bucket —
      the only option for sharded graphs;
    - sorted: per-cycle gather into compile-time-sorted edge order,
      then ``segment_sum(indices_are_sorted=True)``;
    - boundary: sorted gather + cumsum + per-variable boundary
      difference — no scatter at all.  EXPERIMENT-ONLY: the f32
      prefix sum grows with the total edge count, so the boundary
      differences cancel catastrophically at the million-edge scale
      this strategy targets (absolute error ~ulp of the running
      total, which dwarfs the 0.01 tie-breaking noise), and TPUs
      have no f64 to accumulate in.  Valid for throughput A/Bs
      (exp_aggregation, bench_scale) and small problems; not offered
      as a maxsum algo param.
    - ell: dense gather + K-way sum over compile-time per-variable
      edge lists padded to the max degree — no scatter, no sort.
      Numerically safe (each variable's sum is over its own K terms,
      like scatter, just in sorted-edge order) and the shape TPU
      vectorizes best; single-device like the other non-scatter
      paths.
    """
    n_segments = graph.var_costs.shape[0]
    d = graph.var_costs.shape[1]
    if not graph.buckets:
        # Constraint-free DCOP: zero factor buckets means zero
        # incoming messages — the ell/sorted fast paths below would
        # hit jnp.concatenate([]) (ADVICE r5).  Beliefs are just the
        # unary costs.
        zeros = jnp.zeros_like(graph.var_costs)
        return graph.var_costs, zeros
    if graph.agg_ell is not None:
        from pydcop_tpu.ops.ell import gather_reduce

        flats = [msgs.reshape(-1, d) for msgs in f2v]
        flat = flats[0] if len(flats) == 1 else jnp.concatenate(
            flats, axis=0)
        sums = gather_reduce(graph.agg_ell, flat, 0.0, jnp.sum)
        return graph.var_costs + sums, sums
    if graph.agg_perm is not None:
        flats = [msgs.reshape(-1, d) for msgs in f2v]
        flat = flats[0] if len(flats) == 1 else jnp.concatenate(
            flats, axis=0)
        in_order = flat[graph.agg_perm]
        if graph.agg_starts is not None:
            cum = jnp.cumsum(in_order, axis=0)
            cz = jnp.concatenate(
                [jnp.zeros((1, d), cum.dtype), cum], axis=0)
            sums = cz[graph.agg_ends] - cz[graph.agg_starts]
        else:
            sums = jax.ops.segment_sum(
                in_order, graph.agg_sorted_seg,
                num_segments=n_segments, indices_are_sorted=True,
            )
        return graph.var_costs + sums, sums
    sums = jnp.zeros_like(graph.var_costs)
    for bucket, msgs in zip(graph.buckets, f2v):
        flat = msgs.reshape(-1, d)
        seg = bucket.var_ids.reshape(-1)
        sums = sums + jax.ops.segment_sum(
            flat, seg, num_segments=n_segments
        )
    return graph.var_costs + sums, sums


def var_to_factor(graph: CompiledFactorGraph, f2v: Msgs,
                  beliefs: jnp.ndarray, sums: jnp.ndarray) -> Msgs:
    """All variable→factor messages: belief minus own contribution,
    mean-normalized over valid slots (reference maxsum.py:670-674)."""
    out = []
    for bucket, msgs in zip(graph.buckets, f2v):
        valid = graph.var_valid[bucket.var_ids]        # [F, a, D]
        raw = beliefs[bucket.var_ids] - msgs           # own cost + others
        factor_sum = sums[bucket.var_ids] - msgs       # others only
        n_valid = jnp.maximum(
            jnp.sum(valid, axis=-1, keepdims=True), 1
        )
        avg = (
            jnp.sum(jnp.where(valid, factor_sum, 0.0), axis=-1,
                    keepdims=True)
            / n_valid
        )
        # BIG as the message dtype: a float32 literal would silently
        # promote bfloat16 message arrays back to f32.
        out.append(jnp.where(valid, raw - avg,
                             jnp.asarray(BIG, raw.dtype)))
    return tuple(out)


def select_values(graph: CompiledFactorGraph,
                  beliefs: jnp.ndarray) -> jnp.ndarray:
    """Per-variable argmin of belief over valid slots ([V] int32)."""
    masked = jnp.where(graph.var_valid, beliefs, jnp.inf)
    return jnp.argmin(masked[:-1], axis=1).astype(jnp.int32)


def _damp(new: Msgs, old: Msgs, damping: float,
          first: jnp.ndarray) -> Msgs:
    """damped = damping * prev + (1-damping) * new; no damping on the
    first cycle (reference apply_damping with prev=None, maxsum.py:679)."""
    return tuple(
        jnp.where(first, n, damping * o + (1.0 - damping) * n)
        for n, o in zip(new, old)
    )


def superstep(state: MaxSumState, graph: CompiledFactorGraph, *,
              damping: float, damp_vars: bool, damp_factors: bool,
              stability: float,
              prune: Optional[Tuple[Optional[PruneTable], ...]] = None,
              ) -> MaxSumState:
    """One synchronous MaxSum cycle with the reference's exact BSP
    semantics: in cycle k BOTH sides fire from the messages sent in
    cycle k-1 (Jacobi — a factor computation and a variable computation
    each see only last cycle's mail, reference
    SynchronousComputationMixin), with per-edge damping and SAME_COUNT
    send-suppression.  This cycle-for-cycle equivalence with the
    threaded agent runtime is what makes device-vs-thread cost parity
    assertable on large loopy graphs (bench.py cost_parity)."""
    first = state.cycle == 0
    valids = tuple(
        graph.var_valid[b.var_ids] for b in graph.buckets
    )

    f2v_cand = factor_to_var(graph, state.v2f, prune=prune)
    if damp_factors and damping > 0:
        f2v_cand = _damp(f2v_cand, state.f2v, damping, first)

    # Variable side uses the factor messages from the PREVIOUS cycle.
    beliefs, sums = aggregate_beliefs(graph, state.f2v)
    v2f_cand = var_to_factor(graph, state.f2v, beliefs, sums)
    if damp_vars and damping > 0:
        v2f_cand = _damp(v2f_cand, state.v2f, damping, first)

    f2v_new, f2v_count = [], []
    v2f_new, v2f_count = [], []
    all_match = jnp.asarray(True)
    for i, valid in enumerate(valids):
        sent, cnt, match = _send_or_suppress(
            f2v_cand[i], state.f2v[i], state.f2v_count[i],
            stability, valid, first)
        f2v_new.append(sent)
        f2v_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, -1))
        sent, cnt, match = _send_or_suppress(
            v2f_cand[i], state.v2f[i], state.v2f_count[i],
            stability, valid, first)
        v2f_new.append(sent)
        v2f_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, -1))

    return MaxSumState(
        v2f=tuple(v2f_new),
        f2v=tuple(f2v_new),
        v2f_count=tuple(v2f_count),
        f2v_count=tuple(f2v_count),
        stable=all_match & ~first,
        cycle=state.cycle + 1,
    )


def assignment_constraint_cost(graph: CompiledFactorGraph,
                               values: jnp.ndarray) -> jnp.ndarray:
    """Total factor-table cost of an assignment ([V] value indices).

    Padding rows contribute 0 (their tables are all-zero and their
    var_ids point at the sentinel row).  Variable-side costs (including
    tie-breaking noise) are NOT included — this is the constraint cost
    the host-side ``DCOP.solution_cost`` reports for problems whose
    variables carry no intrinsic costs."""
    vals = jnp.concatenate(
        [values, jnp.zeros((1,), dtype=values.dtype)]
    )
    total = jnp.asarray(0.0, dtype=graph.var_costs.dtype)
    for bucket in graph.buckets:
        f, arity = bucket.var_ids.shape
        d = graph.var_costs.shape[1]
        idx = vals[bucket.var_ids]               # [F, arity]
        flat = jnp.zeros((f,), dtype=jnp.int32)
        for p in range(arity):
            flat = flat * d + idx[:, p]
        table = bucket.costs.reshape(f, -1)
        total = total + jnp.sum(
            jnp.take_along_axis(table, flat[:, None], axis=1)
        )
    return total


def run_maxsum_trace(graph: CompiledFactorGraph, max_cycles: int, *,
                     damping: float = 0.5, damp_vars: bool = True,
                     damp_factors: bool = True, stability: float = 0.1,
                     var_base_costs=None,
                     stop_on_convergence: bool = True,
                     prune: bool = False,
                     ) -> Tuple[MaxSumState, jnp.ndarray, jnp.ndarray]:
    """Like run_maxsum, additionally recording the cost of the
    selected assignment after every cycle ([max_cycles] array) — the
    cost-vs-cycle curve used for time-to-equal-cost benchmark claims.
    ``var_base_costs`` ([V, D], noise-free variable costs) makes the
    trace match ``DCOP.solution_cost`` on problems with variable-side
    costs.

    With ``stop_on_convergence`` (the default, matching run_maxsum)
    the loop stops at the fixpoint: the cycle counter freezes at the
    convergence cycle (traced and untraced runs agree — asserted in
    the work-reduction battery) and the rest of the cost array holds
    the final value, so the curve keeps its static [max_cycles]
    shape.  Structured as a while_loop writing each cycle's cost into
    a carried [max_cycles] buffer (``dynamic_update_slice``) rather
    than a scan over a skip-conditional — conditional branch operands
    don't alias on the CPU backend, so a per-cycle ``lax.cond`` was
    measured to cost more than the superstep it skipped.  ``prune``
    uses the same dense/compacted phase alternation as run_maxsum_from
    (identical costs per cycle — pruning never changes values)."""
    pt = prune_tables(graph) if prune else None
    if pt is not None and all(t is None for t in pt):
        pt = None

    def cost_of(values):
        cost = assignment_constraint_cost(graph, values)
        if var_base_costs is not None:
            cost = cost + jnp.sum(jnp.take_along_axis(
                var_base_costs, values[:, None], axis=1))
        return cost

    def make_step(prune_t):
        def step(carry):
            state, costs, last = carry
            state = superstep(
                state, graph, damping=damping, damp_vars=damp_vars,
                damp_factors=damp_factors, stability=stability,
                prune=prune_t,
            )
            beliefs, _ = aggregate_beliefs(graph, state.f2v)
            values = select_values(graph, beliefs)
            cost = cost_of(values)
            costs = jax.lax.dynamic_update_slice(
                costs, cost[None], (state.cycle - 1,))
            return state, costs, cost
        return step

    def done(carry):
        state = carry[0]
        out = state.cycle >= max_cycles
        if stop_on_convergence:
            out = out | state.stable
        return out

    zero = jnp.asarray(0.0, graph.var_costs.dtype)
    carry = (init_state(graph),
             jnp.zeros((max_cycles,), graph.var_costs.dtype), zero)
    step_dense = make_step(None)
    if pt is None:
        carry = jax.lax.while_loop(
            lambda c: ~done(c), step_dense, carry)
    else:
        step_fast = make_step(pt)

        def phases(c):
            c = jax.lax.while_loop(
                lambda c: ~done(c) & ~prune_fits(c[0].v2f, pt),
                step_dense, c)
            c = jax.lax.while_loop(
                lambda c: ~done(c) & prune_fits(c[0].v2f, pt),
                step_fast, c)
            return c

        carry = jax.lax.while_loop(lambda c: ~done(c), phases, carry)
    state, costs, last = carry
    # Early exit leaves the tail unwritten: hold the final cost so
    # the curve stays a valid anytime record at full length.
    costs = jnp.where(
        jnp.arange(max_cycles) >= state.cycle, last, costs)
    beliefs, _ = aggregate_beliefs(graph, state.f2v)
    values = select_values(graph, beliefs)
    return state, values, costs


def run_maxsum(graph: CompiledFactorGraph, max_cycles: int, *,
               damping: float = 0.5, damp_vars: bool = True,
               damp_factors: bool = True, stability: float = 0.1,
               stop_on_convergence: bool = True,
               prune: bool = False,
               ) -> Tuple[MaxSumState, jnp.ndarray]:
    """Full MaxSum run in one XLA program (no host sync per cycle).

    Returns (final state, selected value indices [V]).
    """
    return run_maxsum_from(
        graph, init_state(graph), max_cycles,
        damping=damping, damp_vars=damp_vars,
        damp_factors=damp_factors, stability=stability,
        stop_on_convergence=stop_on_convergence, prune=prune,
    )


def run_maxsum_from(graph: CompiledFactorGraph, state: MaxSumState,
                    extra_cycles: int, *,
                    damping: float = 0.5, damp_vars: bool = True,
                    damp_factors: bool = True, stability: float = 0.1,
                    stop_on_convergence: bool = True,
                    prune: bool = False,
                    ) -> Tuple[MaxSumState, jnp.ndarray]:
    """Run up to ``extra_cycles`` more supersteps from an existing state
    — the warm-start primitive for dynamic DCOPs: after a graph event
    the surviving messages stay in place and the trajectory continues
    instead of restarting from zero (SURVEY §7 "dynamic graphs ...
    warm-starting messages").

    ``prune=True`` enables branch-and-bound pruning of the binary
    factor→variable reductions (:func:`prune_tables`): the solve
    becomes a pair of PHASE loops — a dense loop that runs while some
    factor's survivor set overflows the static gather budget, and a
    compacted fast loop that runs while every factor fits
    (:func:`prune_fits` rides the loop conditions; each body is
    entered only when its kernel is exact, so no per-cycle XLA
    conditional and no hypercube-sized branch-operand copies).  The
    two kernels produce the same values wherever both are legal, so
    the pruned trajectory equals the dense one — pruning changes
    wall-clock, never results."""
    pt = prune_tables(graph) if prune else None
    if pt is not None and all(t is None for t in pt):
        pt = None

    limit = state.cycle + extra_cycles

    def done(s):
        out = s.cycle >= limit
        if stop_on_convergence:
            out = out | s.stable
        return out

    def step_dense(s):
        return superstep(
            s, graph, damping=damping, damp_vars=damp_vars,
            damp_factors=damp_factors, stability=stability,
        )

    if pt is None:
        # The pre-pruning loop, kept VERBATIM (cond form included):
        # even a logically-equivalent condition rewrite compiles a
        # different XLA program, and on mesh runs a different fusion
        # reassociates the all-reduce enough to flip near-tied
        # argmins — the sharded bit-parity tests pin this.
        if stop_on_convergence:
            state = jax.lax.while_loop(
                lambda s: (s.cycle < limit) & ~s.stable,
                step_dense,
                state,
            )
        else:
            state = jax.lax.while_loop(
                lambda s: s.cycle < limit,
                step_dense,
                state,
            )
    else:
        def step_fast(s):
            return superstep(
                s, graph, damping=damping, damp_vars=damp_vars,
                damp_factors=damp_factors, stability=stability,
                prune=pt,
            )

        def fits(s):
            return prune_fits(s.v2f, pt)

        def phases(s):
            # Each outer iteration makes progress: whichever inner
            # condition holds first steps at least one cycle.
            s = jax.lax.while_loop(
                lambda s: ~done(s) & ~fits(s), step_dense, s)
            s = jax.lax.while_loop(
                lambda s: ~done(s) & fits(s), step_fast, s)
            return s

        state = jax.lax.while_loop(lambda s: ~done(s), phases, state)
    beliefs, _ = aggregate_beliefs(graph, state.f2v)
    values = select_values(graph, beliefs)
    return state, values

"""Opt-in per-step computation trace, written as CSV.

Reference parity: pydcop/infrastructure/stats.py (column schema
:49-64, set_stats_file :71, trace_computation :81 — off by default).

Columns: timestamp, computation, step duration, messages in/out,
message sizes in/out, current value.

This module is now a thin shim over the observability subsystem: every
row is also forwarded to :data:`pydcop_tpu.observability.trace.tracer`
as a ``computation_step`` instant (when tracing is enabled), so the
legacy CSV and a Chrome/JSONL trace of the same run tell one story.
An ``atexit`` close is registered the first time a file is opened, so
an interrupted run still flushes its rows.
"""

import atexit
import csv
import threading
import time
from typing import Optional

from pydcop_tpu.observability.trace import tracer

COLUMNS = [
    "time",
    "computation",
    "duration",
    "msg_in_count",
    "msg_in_size",
    "msg_out_count",
    "msg_out_size",
    "value",
]

_lock = threading.Lock()
_stats_file = None
_writer = None
_atexit_registered = False


def set_stats_file(path: Optional[str]):
    """Enable (or disable with None) step tracing to a CSV file.

    The swap is atomic: the new file is opened (and its header
    written) BEFORE the old writer is touched, so a failing ``open``
    — bad directory, permissions — raises while the previous tracing
    state keeps working.  (The old implementation closed the previous
    file first; an open() error then left the globals half-cleared
    with the caller believing tracing was still on.)
    """
    global _stats_file, _writer, _atexit_registered
    with _lock:
        new_file = new_writer = None
        if path is not None:
            new_file = open(path, "w", newline="", encoding="utf-8")
            new_writer = csv.writer(new_file)
            new_writer.writerow(COLUMNS)
        old_file = _stats_file
        _stats_file, _writer = new_file, new_writer
        if old_file is not None:
            old_file.close()
        if new_file is not None and not _atexit_registered:
            atexit.register(close)
            _atexit_registered = True


def close():
    """Flush + close the CSV; idempotent (registered atexit so an
    interrupted run keeps the rows written so far)."""
    global _stats_file, _writer
    with _lock:
        if _stats_file is not None:
            try:
                _stats_file.close()
            except Exception:
                pass
            _stats_file = None
            _writer = None


def tracing_enabled() -> bool:
    return _stats_file is not None


def trace_computation(computation: str, duration: float,
                      msg_in_count: int = 0, msg_in_size: int = 0,
                      msg_out_count: int = 0, msg_out_size: int = 0,
                      value=None):
    """Append one step row (no-op unless set_stats_file was called).

    Independently of the CSV state, the same event lands on the
    observability tracer when it is enabled — one instrumentation
    call site, two sinks.
    """
    if tracer.enabled:
        tracer.instant(
            "computation_step", "agent", computation=computation,
            duration=duration, msg_in_count=msg_in_count,
            msg_in_size=msg_in_size, msg_out_count=msg_out_count,
            msg_out_size=msg_out_size,
            value=None if value is None else str(value),
        )
    with _lock:
        if _writer is None:
            return
        _writer.writerow([
            f"{time.time():.6f}", computation, f"{duration:.6f}",
            msg_in_count, msg_in_size, msg_out_count, msg_out_size,
            "" if value is None else value,
        ])
        _stats_file.flush()

"""Dynamic-DCOP event scripts.

Reference parity: pydcop/dcop/scenario.py (EventAction :37, DcopEvent :55,
Scenario :95); YAML format docs/usage/file_formats/scenario_format.yml.
"""

from typing import Dict, Iterable, List, Optional

from pydcop_tpu.utils.simple_repr import SimpleRepr


class EventAction(SimpleRepr):
    """A single action in an event: e.g. remove_agent / add_agent."""

    def __init__(self, type: str, **args):
        self._type = type
        self._args = dict(args)

    @property
    def type(self) -> str:
        return self._type

    @property
    def args(self) -> Dict:
        return dict(self._args)

    def _simple_repr(self):
        r = {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "type": self._type,
        }
        r.update(self._args)
        return r

    @classmethod
    def _from_repr(cls, r):
        args = {k: v for k, v in r.items()
                if k != "type" and not k.startswith("__")}
        return cls(r["type"], **args)

    def __repr__(self):
        return f"EventAction({self._type}, {self._args})"

    def __eq__(self, other):
        return (
            isinstance(other, EventAction)
            and self._type == other._type
            and self._args == other._args
        )


class DcopEvent(SimpleRepr):
    """An event: either a delay or a list of simultaneous actions."""

    def __init__(self, id: str, delay: Optional[float] = None,
                 actions: Optional[List[EventAction]] = None):
        self._id = id
        self._delay = delay
        self._actions = actions

    @property
    def id(self) -> str:
        return self._id

    @property
    def delay(self) -> Optional[float]:
        return self._delay

    @property
    def actions(self) -> Optional[List[EventAction]]:
        return self._actions

    @property
    def is_delay(self) -> bool:
        return self._delay is not None

    def __repr__(self):
        if self.is_delay:
            return f"DcopEvent(delay {self._delay})"
        return f"DcopEvent({self._id}, {self._actions})"

    def __eq__(self, other):
        return (
            isinstance(other, DcopEvent)
            and self._id == other._id
            and self._delay == other._delay
            and self._actions == other._actions
        )


class Scenario(SimpleRepr):
    """An ordered list of events applied to a running DCOP."""

    def __init__(self, events: Optional[Iterable[DcopEvent]] = None):
        self._events = list(events) if events else []

    @property
    def events(self) -> List[DcopEvent]:
        return list(self._events)

    def add_event(self, event: DcopEvent):
        self._events.append(event)

    def __iter__(self):
        return iter(self._events)

    def __len__(self):
        return len(self._events)

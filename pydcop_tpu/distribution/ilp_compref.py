"""ilp_compref: optimal ILP over weighted communication + hosting costs.

Reference parity: pydcop/distribution/ilp_compref.py (distribute :79,
AAMAS-18; RATIO_HOST_COMM weighting; PuLP replaced by scipy milp).
"""

from pydcop_tpu.distribution._base import (
    RATIO_HOST_COMM,
    distribution_cost_impl,
    ilp_place,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None,
               timeout=None, **_):
    return ilp_place(
        computation_graph, agentsdef, hints,
        computation_memory, communication_load,
        timeout=timeout,
        comm_weight=RATIO_HOST_COMM,
        hosting_weight=1 - RATIO_HOST_COMM,
    )


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return distribution_cost_impl(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load)

"""DPOP bench: level-batched jitted sweep vs per-node numpy sweep.

Config #3 of BASELINE.md (tree-structured DCOP, total solve time).
Prints one JSON line per problem size with both engines' times and the
(identical) optimal cost.

Run: python benchmarks/bench_dpop.py  (honors the wedged-tunnel guard
via pydcop_tpu.utils.cleanenv re-exec, like bench.py).
"""

import json
import sys
import time

import numpy as np


def make_tree_dcop(n, d, seed=0):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("bench", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        p = rng.integers(0, i)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[p], vs[i]], rng.random((d, d)), f"c{i}"
        ))
    return dcop


def _ensure_live_backend():
    from pydcop_tpu.utils.cleanenv import ensure_live_backend

    ensure_live_backend(tag="bench_dpop")


def main():
    _ensure_live_backend()
    from pydcop_tpu.algorithms import AlgorithmDef
    from pydcop_tpu.algorithms.dpop import solve_on_device

    for n, d in ((3000, 3), (10000, 8)):
        dcop = make_tree_dcop(n, d)
        jit_algo = AlgorithmDef.build_with_default_param(
            "dpop", {"engine": "jit"}, mode="min"
        )
        np_algo = AlgorithmDef.build_with_default_param(
            "dpop", {"engine": "numpy"}, mode="min"
        )
        # Warm the kernel cache so the timed run is compile-free.
        solve_on_device(dcop, jit_algo)
        t0 = time.perf_counter()
        r_jit = solve_on_device(dcop, jit_algo)
        t1 = time.perf_counter()
        r_np = solve_on_device(dcop, np_algo)
        t2 = time.perf_counter()
        assert abs(
            r_jit.metrics["device_cost"] - r_np.metrics["device_cost"]
        ) < 1e-2, "cost parity violated"
        print(json.dumps({
            "metric": f"dpop_solve_time_{n}var_d{d}",
            "value": round(t1 - t0, 4),
            "unit": "s",
            "vs_baseline": round((t2 - t1) / (t1 - t0), 2),
            "baseline": "per-node numpy sweep",
            "numpy_s": round(t2 - t1, 4),
            "cost": round(r_jit.metrics["device_cost"], 3),
            "kernel_calls": r_jit.metrics["kernel_calls"],
        }))


if __name__ == "__main__":
    main()

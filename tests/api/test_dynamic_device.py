"""Device-path dynamic DCOP tests (VERDICT #7).

The DynamicMaxSumEngine must (a) warm-start across run segments with no
behavioral difference vs one long run, (b) absorb factor edits through
padding slack without recompiling, (c) carry messages over a recompile
when an edit outgrows the slack, and (d) keep cost continuity across
events.
"""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.dynamic import DynamicMaxSumEngine

D3 = Domain("colors", "color", [0, 1, 2])


def _ring(n=12, seed=0):
    """Ring of n variables with equality-penalty constraints."""
    rng = np.random.default_rng(seed)
    variables = [Variable(f"v{i}", D3) for i in range(n)]
    eq = np.eye(3)
    constraints = [
        NAryMatrixRelation(
            [variables[i], variables[(i + 1) % n]], eq, f"c{i}")
        for i in range(n)
    ]
    return variables, constraints


def test_split_run_equals_single_run():
    variables, constraints = _ring()
    e1 = DynamicMaxSumEngine(variables, constraints, noise_seed=4)
    r1a = e1.run(40, stop_on_convergence=False)
    r1b = e1.run(40, stop_on_convergence=False)
    e2 = DynamicMaxSumEngine(variables, constraints, noise_seed=4)
    r2 = e2.run(80, stop_on_convergence=False)
    assert r1b.cycles == r2.cycles == 80
    assert r1b.assignment == r2.assignment


def test_change_factor_no_recompile():
    variables, constraints = _ring(6)
    eng = DynamicMaxSumEngine(variables, constraints, noise_seed=1)
    res = eng.run(60)
    assert res.metrics["recompiles"] == 0
    base_conflicts = sum(
        res.assignment[f"v{i}"] == res.assignment[f"v{(i + 1) % 6}"]
        for i in range(6)
    )
    assert base_conflicts == 0
    # Flip c0 into an equality PREFERENCE (penalize differing): the
    # fixpoint must adapt so v0 == v1.
    neq = 1.0 - np.eye(3)
    eng.change_factor("c0", NAryMatrixRelation(
        [variables[0], variables[1]], neq, "c0"))
    res2 = eng.run(120)
    assert res2.metrics["recompiles"] == 0  # slack edit, same program
    assert res2.assignment["v0"] == res2.assignment["v1"]
    assert res2.cycles > res.cycles  # warm continuation, not a restart


def test_remove_and_add_factor_within_slack():
    variables, constraints = _ring(8)
    eng = DynamicMaxSumEngine(
        variables, constraints, noise_seed=2, slack=0.5)
    eng.run(40)
    eng.remove_factor("c3")
    assert "c3" not in eng.factors
    eq = np.eye(3)
    # New chord factor fits the freed/slack rows: no recompile.
    eng.add_factor(NAryMatrixRelation(
        [variables[0], variables[4]], eq, "chord"))
    res = eng.run(80)
    assert res.metrics["recompiles"] == 0
    # The chord constraint is active: v0 != v4.
    assert res.assignment["v0"] != res.assignment["v4"]


def test_add_beyond_slack_recompiles_and_warm_starts():
    variables, constraints = _ring(8)
    eng = DynamicMaxSumEngine(
        variables, constraints, noise_seed=3, slack=0.0)
    res0 = eng.run(60)
    cost0 = eng.cost(res0.assignment)
    # slack=0 still keeps >=1 spare row (implementation guarantees
    # n+1); exhaust it, then one more forces a recompile.
    eq = np.eye(3)
    added = 0
    while eng._free[0]:
        i = added + 1
        eng.add_factor(NAryMatrixRelation(
            [variables[0], variables[i + 1]], eq, f"x{added}"))
        added += 1
    eng.add_factor(NAryMatrixRelation(
        [variables[2], variables[6]], eq, "overflow"))
    res1 = eng.run(120)
    assert res1.metrics["recompiles"] >= 1
    # Warm start survived the recompile: the cycle counter continued.
    assert res1.cycles > res0.cycles
    # Cost continuity: the pre-event solution was conflict-free on the
    # surviving constraints; the warm-started run must not regress on
    # them (only the new constraints add requirements).
    cost1 = eng.cost(res1.assignment)
    assert cost1 <= cost0 + 1.0


def test_add_variable_recompiles_and_links():
    variables, constraints = _ring(6)
    eng = DynamicMaxSumEngine(variables, constraints, noise_seed=5)
    eng.run(40)
    w = Variable("w0", D3)
    eq = np.eye(3)
    eng.add_factor(NAryMatrixRelation([variables[0], w], eq, "cw"))
    res = eng.run(120)
    assert "w0" in res.assignment
    assert res.assignment["w0"] != res.assignment["v0"]
    assert res.metrics["recompiles"] >= 1


def test_cost_continuity_across_noop_event():
    """An event that does not change the problem must not perturb the
    trajectory at all: state is identical to just continuing."""
    variables, constraints = _ring(10)
    eng = DynamicMaxSumEngine(variables, constraints, noise_seed=6)
    res_a = eng.run(50, stop_on_convergence=False)
    # remove + re-add the same factor: graph returns to the same math.
    c5 = eng.factors["c5"]
    eng.remove_factor("c5")
    eng.add_factor(c5)
    res_b = eng.run(50, stop_on_convergence=False)
    # The edge messages were reset by the edit, but the surviving state
    # pulls the trajectory back: same conflict-free fixpoint.
    assert eng.cost(res_b.assignment) <= eng.cost(res_a.assignment)

"""Pure JAX kernels for the message-passing hot loops.

Everything in this package is functional, shape-static and jit-safe:
no python control flow on traced values, no host callbacks.  These are
the TPU equivalents of the reference's per-computation python loops
(maxsum.factor_costs_for_var, dpop join/projection, dsa/mgm best-response).
"""

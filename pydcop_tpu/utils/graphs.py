"""Graph helpers over variables and constraints.

Reference parity: pydcop/utils/graphs.py (as_networkx_graph :131,
as_networkx_bipartite_graph :157, calc_diameter :86, cycles_count
:263, graph_diameter :270, all_pairs :289).

Structural metrics are computed with plain BFS over adjacency dicts
(no graph-library dependency on the hot paths); the networkx bridges
are kept for interop/analysis since generators already use networkx.
"""

import itertools
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


def constraint_adjacency(variables, constraints) -> Dict[str, Set[str]]:
    """Variable adjacency: two variables are neighbors when they share
    a constraint scope."""
    adj: Dict[str, Set[str]] = {v.name: set() for v in variables}
    for c in constraints:
        names = [v.name for v in c.dimensions]
        for a, b in itertools.combinations(names, 2):
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set()).add(a)
    return adj


def _bfs_depths(adj: Dict[str, Set[str]], root: str) -> Dict[str, int]:
    depths = {root: 0}
    queue = deque([root])
    while queue:
        node = queue.popleft()
        for neighbor in adj.get(node, ()):
            if neighbor not in depths:
                depths[neighbor] = depths[node] + 1
                queue.append(neighbor)
    return depths


# Above this node count, component diameters fall back to the
# double-BFS-sweep lower bound (exact on trees, very tight on sparse
# graphs) instead of all-node BFS — O(V+E) instead of O(V*(V+E)).
EXACT_DIAMETER_LIMIT = 2000


def components(adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Connected components of an adjacency dict."""
    seen: Set[str] = set()
    out = []
    for root in adj:
        if root in seen:
            continue
        component = set(_bfs_depths(adj, root))
        seen |= component
        out.append(component)
    return out


def calc_diameter(adj: Dict[str, Set[str]],
                  exact: bool = True) -> int:
    """Diameter of an adjacency dict.

    exact=True: max eccentricity by BFS from every node.
    exact=False: double-sweep lower bound (one BFS to find the
    furthest node, one BFS from it)."""
    if not adj:
        return 0
    if exact:
        best = 0
        for root in adj:
            depths = _bfs_depths(adj, root)
            if depths:
                best = max(best, max(depths.values()))
        return best
    root = next(iter(adj))
    depths = _bfs_depths(adj, root)
    far = max(depths, key=depths.get)
    depths = _bfs_depths(adj, far)
    return max(depths.values(), default=0)


def graph_diameter(variables, constraints,
                   adj: Optional[Dict[str, Set[str]]] = None,
                   ) -> List[int]:
    """Diameter of each connected component of the constraint graph
    (reference graphs.py:270).  Components above EXACT_DIAMETER_LIMIT
    nodes use the double-sweep estimate."""
    if adj is None:
        adj = constraint_adjacency(variables, constraints)
    diameters = []
    for component in components(adj):
        sub = {n: adj[n] & component for n in component}
        diameters.append(calc_diameter(
            sub, exact=len(component) <= EXACT_DIAMETER_LIMIT
        ))
    return diameters


def cycles_count(variables, constraints,
                 adj: Optional[Dict[str, Set[str]]] = None) -> int:
    """Number of independent cycles of the constraint graph
    (E - V + components, reference graphs.py:263)."""
    if adj is None:
        adj = constraint_adjacency(variables, constraints)
    n_edges = sum(len(neigh) for neigh in adj.values()) // 2
    return n_edges - len(adj) + len(components(adj))


def all_pairs(elements: Sequence) -> Iterable[Tuple]:
    """All unordered pairs (reference graphs.py:289)."""
    return itertools.combinations(elements, 2)


# -- networkx bridges (analysis / display interop) -------------------- #


def as_networkx_graph(variables, constraints):
    """Constraint graph as a networkx Graph (reference :131)."""
    import networkx as nx

    graph = nx.Graph()
    graph.add_nodes_from(v.name for v in variables)
    for c in constraints:
        names = [v.name for v in c.dimensions]
        graph.add_edges_from(itertools.combinations(names, 2))
    return graph


def as_networkx_bipartite_graph(variables, constraints):
    """Factor graph as a networkx bipartite Graph (reference :157)."""
    import networkx as nx

    graph = nx.Graph()
    graph.add_nodes_from((v.name for v in variables), bipartite=0)
    graph.add_nodes_from((c.name for c in constraints), bipartite=1)
    for c in constraints:
        graph.add_edges_from(
            (c.name, v.name) for v in c.dimensions
        )
    return graph

"""Static consistency gate (the reference runs mypy, Makefile:20;
mypy is not installable in this zero-egress image, so this is the
stdlib equivalent): byte-compile every source file, then import every
module of the package under a scrubbed CPU backend — catching syntax
errors, missing imports, and module-level typos across the whole tree
in one pass.

Run:  python tools/static_check.py      (exit 0 = clean)
"""

import compileall
import importlib
import os
import pkgutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, REPO)

    ok = compileall.compile_dir(
        os.path.join(REPO, "pydcop_tpu"), quiet=1, force=True)
    ok &= compileall.compile_dir(
        os.path.join(REPO, "tests"), quiet=1, force=True)
    if not ok:
        print("static_check: byte-compilation failed")
        return 1

    import pydcop_tpu

    failures = []
    for mod in pkgutil.walk_packages(
            pydcop_tpu.__path__, prefix="pydcop_tpu."):
        try:
            importlib.import_module(mod.name)
        except Exception as exc:  # noqa: BLE001 - report, don't mask
            failures.append((mod.name, f"{type(exc).__name__}: {exc}"))
    if failures:
        print(f"static_check: {len(failures)} module(s) failed to "
              "import:")
        for name, err in failures:
            print(f"  {name}: {err}")
        return 1
    print("static_check: all modules compile and import cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())

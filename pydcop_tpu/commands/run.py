"""``pydcop run``: solve a *dynamic* DCOP — scenario events (agent
departures) fire during the run, replicas keep computations alive.

Reference parity: pydcop/commands/run.py (run_cmd :314: solve +
``--scenario`` events + replication ``--ktarget``).  Result JSON shape
matches ``pydcop solve``; replication/repair state is reported under
``replication``.
"""

import logging

from pydcop_tpu.commands._utils import build_algo_def, emit_result

logger = logging.getLogger("pydcop.cli.run")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "run", help="run a dynamic DCOP with scenario events")
    parser.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    parser.add_argument("-a", "--algo", required=True,
                        help="algorithm name")
    parser.add_argument("-p", "--algo_params", action="append",
                        help="algorithm parameter as name:value")
    parser.add_argument("-d", "--distribution", default="oneagent",
                        help="distribution method or file")
    parser.add_argument("-s", "--scenario", required=True,
                        help="scenario yaml file")
    parser.add_argument("-r", "--replication_method",
                        default="dist_ucs_hostingcosts",
                        choices=["dist_ucs_hostingcosts"],
                        help="replication method (reference parity; "
                             "'dist_ucs_hostingcosts' is the only one "
                             "the reference ships, and the only one "
                             "here)")
    parser.add_argument("-k", "--ktarget", type=int, default=3,
                        help="number of replicas per computation")
    parser.add_argument("--repair", default="device",
                        choices=["device", "distributed"],
                        help="how the repair DCOP is solved on agent "
                             "departure: centrally on the device "
                             "engine (default) or distributed among "
                             "the candidate agents (reference "
                             "architecture)")
    parser.add_argument("-m", "--mode", default="thread",
                        choices=["thread", "process", "device"],
                        help="execution mode: 'thread' = agent runtime "
                             "with replication/repair; 'process' = one "
                             "OS process per agent over HTTP "
                             "(reference run.py:387); 'device' = "
                             "dynamic device engine (warm-started "
                             "across events, placement re-homed on "
                             "agent departure)")
    parser.add_argument("-c", "--cycles", type=int, default=0,
                        help="max cycles (0: unbounded)")
    parser.add_argument("--collect_on", default="value_change",
                        choices=["value_change", "cycle_change", "period"])
    parser.add_argument("--period", type=float, default=1.0)
    parser.add_argument("--run_metrics", default=None)
    parser.add_argument("--end_metrics", default=None)
    parser.add_argument("--infinity", type=float, default=float("inf"))
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.dcop.yamldcop import (
        load_dcop_from_file,
        load_scenario_from_file,
    )
    from pydcop_tpu.infrastructure.run import (
        PROCESS_READY_TIMEOUT,
        THREAD_READY_TIMEOUT,
        _build_distribution,
        run_local_process_dcop,
        run_local_thread_dcop,
    )

    from pydcop_tpu.algorithms import AlgorithmDef

    dcop = load_dcop_from_file(args.dcop_files)
    scenario = load_scenario_from_file(args.scenario)
    algo_def = build_algo_def(args.algo, args.algo_params, dcop.objective)

    if args.mode == "device":
        if args.repair != "device":
            logger.warning(
                "--repair %s is an agent-mode option; device-mode runs "
                "re-home departed agents' computations directly "
                "(ignored)", args.repair,
            )
        return _run_device_cmd(args, dcop, scenario, algo_def)
    algo_module = load_algorithm_module(algo_def.algo)
    # -c bounds algorithms exposing a stop_cycle parameter (same
    # mapping as solve, infrastructure/run.py solve_with_agents).
    if args.cycles:
        param_names = {p.name for p in algo_module.algo_params}
        if ("stop_cycle" in param_names
                and not algo_def.params.get("stop_cycle")):
            params = algo_def.params
            params["stop_cycle"] = args.cycles
            algo_def = AlgorithmDef(algo_def.algo, params, algo_def.mode)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    distribution = _build_distribution(
        dcop, cg, algo_module, args.distribution
    )

    collector = None
    if args.run_metrics:
        from pydcop_tpu.commands.metrics_io import add_csvline

        def collector(metrics):
            add_csvline(args.run_metrics, args.collect_on, metrics)

    timeout = args.timeout if args.timeout is not None else 20.0
    runner = (run_local_process_dcop if args.mode == "process"
              else run_local_thread_dcop)
    orchestrator = runner(
        algo_def, cg, distribution, dcop, infinity=args.infinity,
        replication=True, collector=collector,
        collect_moment=args.collect_on, collect_period=args.period,
        repair_mode=args.repair,
    )
    stopped = False
    try:
        if not orchestrator.wait_ready(
                PROCESS_READY_TIMEOUT if args.mode == "process"
                else THREAD_READY_TIMEOUT):
            print("Error: agents did not become ready")
            return 3
        orchestrator.deploy_computations()
        replica_dist = orchestrator.start_replication(args.ktarget)
        orchestrator.run(scenario=scenario, timeout=timeout)
        orchestrator.stop_agents(5)
        stopped = True
        metrics = orchestrator.end_metrics()
        result = {
            "status": metrics["status"],
            "assignment": {
                k: v for k, v in metrics["assignment"].items()
                if k in dcop.variables
            },
            "cost": metrics["cost"],
            "violation": metrics["violation"],
            "time": metrics["time"],
            "msg_count": metrics["msg_count"],
            "msg_size": metrics["msg_size"],
            "cycle": metrics["cycle"],
            "agt_metrics": metrics["agt_metrics"],
            "replication": {
                "ktarget": args.ktarget,
                "replica_distribution": replica_dist.mapping,
                "repaired": sorted(
                    orchestrator.mgt.repaired_computations
                ),
            },
            "backend": args.mode,
        }
    finally:
        if not stopped:
            orchestrator.stop_agents(5)
        orchestrator.stop()

    if args.run_metrics or args.end_metrics:
        from pydcop_tpu.commands.metrics_io import add_csvline

        # Run metrics streamed live above; both files always get the
        # final summary row so they exist even on event-less runs.
        for path in (args.run_metrics, args.end_metrics):
            if path:
                add_csvline(path, args.collect_on, result)

    emit_result(result, args.output)
    return 0


# Cycles run per event-delay second in device mode: device cycles are
# orders of magnitude faster than wall-clock agent cycles, so delays
# are interpreted as computation budget rather than sleeps.
DEVICE_CYCLES_PER_DELAY_SECOND = 200
# Delay budgets run in fixed-size chunks so every segment reuses ONE
# compiled program (max_cycles is a static jit key; distinct per-delay
# cycle counts would each trigger a full XLA compile).
DEVICE_RUN_CHUNK = 200


def _run_device_cmd(args, dcop, scenario, algo_def) -> int:
    """Dynamic run on the device engine: scenario events are applied to
    a warm-started DynamicMaxSumEngine (messages survive every event,
    cost stays continuous), and agent departures re-home the departed
    agent's computations in the placement map — the device-side
    analogue of replica-based repair (thread mode solves a repair DCOP
    instead, infrastructure/orchestrator.py)."""
    import time as _time

    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.engine.dynamic import DynamicMaxSumEngine
    from pydcop_tpu.engine.multihost import initialize_multihost

    initialize_multihost()
    from pydcop_tpu.infrastructure.run import _build_distribution

    if algo_def.algo not in ("maxsum", "amaxsum", "maxsum_dynamic"):
        print(
            f"Error: device-mode dynamic runs support the maxsum "
            f"family, not {algo_def.algo!r} (use --mode thread)"
        )
        return 2

    algo_module = load_algorithm_module(algo_def.algo)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    distribution = _build_distribution(
        dcop, cg, algo_module, args.distribution)
    placement = {
        c: a for a in distribution.agents
        for c in distribution.computations_hosted(a)
    }
    live_agents = set(distribution.agents)

    params = algo_def.params
    engine = DynamicMaxSumEngine(
        list(dcop.variables.values()),
        list(dcop.constraints.values()),
        mode=dcop.objective,
        noise_level=params.get("noise", 0.01),
        damping=params.get("damping", 0.5),
        damping_nodes=params.get("damping_nodes", "both"),
        stability=params.get("stability", 0.1),
    )

    t0 = _time.perf_counter()
    repaired = set()
    events_log = []
    last = engine.run(1, stop_on_convergence=False)
    # Fractional chunk budgets carry over between delay events so the
    # cycle count stays proportional to the scenario's timing while
    # every segment reuses ONE compiled program of DEVICE_RUN_CHUNK
    # cycles.
    budget_acc = 0.0
    for event in scenario:
        if event.is_delay:
            budget_acc += max(
                1.0, event.delay * DEVICE_CYCLES_PER_DELAY_SECOND)
            while budget_acc >= DEVICE_RUN_CHUNK:
                chunk = DEVICE_RUN_CHUNK
                if args.cycles:
                    chunk = min(chunk, args.cycles - last.cycles)
                if chunk <= 0:
                    budget_acc = 0.0
                    break
                last = engine.run(chunk, stop_on_convergence=False)
                budget_acc -= chunk
            continue
        for action in event.actions or []:
            if action.type == "remove_agent":
                agent = action.args["agent"]
                live_agents.discard(agent)
                orphans = [
                    c for c, a in placement.items() if a == agent
                ]
                # Re-home on the least-loaded survivors.
                for c in sorted(orphans):
                    if not live_agents:
                        break
                    # Tie-break on the agent name so re-homing is
                    # reproducible across runs (set iteration order is
                    # hash-randomized).
                    target = min(
                        sorted(live_agents),
                        key=lambda a: sum(
                            1 for x in placement.values() if x == a
                        ),
                    )
                    placement[c] = target
                    repaired.add(c)
            elif action.type == "add_agent":
                live_agents.add(action.args["agent"])
            else:
                logger.warning(
                    "Unknown scenario action %r ignored in device "
                    "mode", action.type)
        # Snapshot at event time: the warm-started engine keeps its
        # cycle counter and message state across the event — the
        # continuity evidence (the trajectory-preservation math itself
        # is asserted in tests/api/test_dynamic_device.py
        # split-run == single-run).
        events_log.append({
            "id": event.id,
            "cycle": last.cycles,
            "cost": engine.cost(last.assignment),
        })

    # --cycles bounds the TOTAL cycle count: the scenario's delay
    # budgets already consumed `last.cycles`, so the final run gets only
    # the remainder (ADVICE r2: previously -c was additional cycles and
    # runs could exceed the user's bound).
    if args.cycles:
        max_cycles = max(0, args.cycles - last.cycles)
    else:
        max_cycles = 2000
    final = engine.run(max_cycles) if max_cycles > 0 else last
    cost, violations = dcop.solution_cost(final.assignment)
    result = {
        "status": "FINISHED" if final.converged else "TIMEOUT",
        "assignment": final.assignment,
        "cost": cost,
        "violation": violations,
        "time": _time.perf_counter() - t0,
        "cycle": final.cycles,
        "events": events_log,
        "replication": {
            "ktarget": args.ktarget,
            "repaired": sorted(repaired),
            "placement_agents": sorted(live_agents),
        },
        "recompiles": final.metrics["recompiles"],
        "backend": "device",
    }
    emit_result(result, args.output)
    return 0

"""MaxSum decimation tests (device-path extension beyond the
reference, arXiv:1706.02209): alternating message passing with
clamping the most confident variables must substantially improve
solution quality on loopy graphs, where plain MaxSum oscillates.
"""

import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation


def loopy_coloring(n: int, seed: int, density: float = 2.2) -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"loopy{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    eq = np.eye(3)
    seen, k = set(), 0
    while k < int(n * density):
        i, j = rng.choice(n, 2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], eq, f"c{k}"))
        k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def test_decimation_beats_plain_maxsum_on_loopy_graphs():
    """Aggregate over seeded dense instances: decimated MaxSum ends
    with far fewer conflicts (measured: plain ~20-30 vs decimated <=5
    per 150-var/330-edge instance)."""
    plain_costs, dec_costs = [], []
    for seed in (1, 2):
        plain = solve(
            loopy_coloring(150, seed), "maxsum", backend="device",
            max_cycles=400)
        plain_costs.append(plain["cost"])
        dec = solve(
            loopy_coloring(150, seed), "maxsum", backend="device",
            max_cycles=3000, algo_params={"decimation": 10})
        dec_costs.append(dec["cost"])
    assert np.mean(dec_costs) < np.mean(plain_costs)
    assert np.mean(dec_costs) <= 8


def test_decimation_fixes_every_variable():
    res = solve(
        loopy_coloring(40, 0), "maxsum", backend="device",
        max_cycles=2000, algo_params={"decimation": 20})
    assert res["status"] == "FINISHED"
    assert res["metrics"]["decimated_vars"] == 40
    assert len(res["assignment"]) == 40


def test_decimation_zero_is_reference_behavior():
    """decimation:0 (the default) must leave the plain engine path
    untouched — same cost as not passing the parameter at all."""
    r1 = solve(
        loopy_coloring(60, 3), "maxsum", backend="device",
        max_cycles=200)
    r2 = solve(
        loopy_coloring(60, 3), "maxsum", backend="device",
        max_cycles=200, algo_params={"decimation": 0})
    assert r1["cost"] == r2["cost"]
    assert r1["assignment"] == r2["assignment"]


def test_decimation_exact_on_trees():
    """On a tree, decimation must not hurt: BP is already exact, and
    clamping confident variables keeps the optimum."""
    rng = np.random.default_rng(5)
    dom = Domain("d", "", [0, 1, 2])
    dcop = DCOP("tree", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(30)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, 30):
        j = int(rng.integers(0, i))
        table = rng.integers(0, 9, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[j], vs[i]], table, f"c{i}"))
    dcop.add_agents([AgentDef("a0")])
    exact = solve(dcop, "dpop", backend="device")
    dec = solve(
        dcop, "maxsum", backend="device", max_cycles=3000,
        algo_params={"decimation": 10, "stability": 1e-6,
                     "noise": 0.001},
    )
    assert dec["cost"] == pytest.approx(exact["cost"], abs=1e-4)
"""Test configuration.

The CPU-backend forcing (8 virtual devices, JAX_PLATFORMS=cpu, axon
plugin env cleared) lives in the repo-root ``conftest.py`` so the
doctest gate shares it; pytest loads that conftest before this one for
everything under tests/, so this file only registers markers.
"""


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute statistical tests (deselect with "
        "-m 'not slow'; they still run by default)",
    )

"""Repair-as-DCOP: rebuild a distribution after agent departures.

Reference parity: pydcop/reparation/__init__.py (:39
create_computation_hosted_constraint, :70
create_agent_capacity_constraint, :117 create_agent_hosting_constraint,
:158 create_agent_comp_comm_constraint).

The repair problem is itself a DCOP over binary variables
``x_<computation>_<agent>`` ("computation is hosted on agent"):

- hard: each orphaned computation is hosted exactly once;
- hard: an agent's added load fits its remaining capacity;
- soft: hosting costs of the chosen (agent, computation) pairs;
- soft: communication cost between a candidate computation and its
  neighbor computations, given where those are hosted.

TPU note: unlike the reference — which solves this DCOP with MaxSum
message-passing among the candidate agents — pydcop-tpu solves the
repair DCOP *on device* with the batched engine (see
``pydcop_tpu.infrastructure.orchestrator.Orchestrator.remove_agent``):
the problem is small (|orphans| x |candidates| binary variables), so a
single jitted solve is faster than any distributed protocol round.
"""

from typing import Callable, Dict, Iterable, List, Tuple

from pydcop_tpu.dcop.objects import BinaryVariable
from pydcop_tpu.dcop.relations import Constraint, NAryFunctionRelation

DEFAULT_INFINITY = 10_000


def binary_variable_name(computation: str, agent: str,
                         suffix: str = "") -> str:
    return f"x_{computation}_{agent}{suffix}"


def create_binary_variables_for(
    orphaned: Iterable[str], candidates: Dict[str, List[str]],
    suffix: str = "",
) -> Dict[Tuple[str, str], BinaryVariable]:
    """One x_c_a variable per (orphaned computation, candidate agent).

    ``suffix`` makes names unique per repair round (e.g. "__r3"):
    distributed repair deploys these as live computations, and
    round-unique names make any straggler message from a previous
    round unroutable by construction.
    """
    variables = {}
    for comp in orphaned:
        for agent in candidates[comp]:
            variables[(comp, agent)] = BinaryVariable(
                binary_variable_name(comp, agent, suffix)
            )
    return variables


def create_computation_hosted_constraint(
    computation: str,
    comp_variables: List[BinaryVariable],
    infinity: float = DEFAULT_INFINITY,
) -> Constraint:
    """Hard: exactly one candidate hosts `computation`
    (reference :39-68)."""

    def hosted(*values):
        return 0 if sum(values) == 1 else infinity

    return NAryFunctionRelation(
        hosted, list(comp_variables), name=f"c_hosted_{computation}"
    )


def create_agent_capacity_constraint(
    agent: str,
    remaining_capacity: float,
    footprints: Dict[str, float],
    agent_variables: Dict[str, BinaryVariable],
    infinity: float = DEFAULT_INFINITY,
) -> Constraint:
    """Hard: total footprint accepted by `agent` fits its remaining
    capacity (reference :70-114).

    `footprints` and `agent_variables` are keyed by computation name.
    """
    comps = sorted(agent_variables)
    variables = [agent_variables[c] for c in comps]
    weights = [footprints[c] for c in comps]

    def capacity(*values):
        load = sum(w * v for w, v in zip(weights, values))
        return 0 if load <= remaining_capacity else infinity

    return NAryFunctionRelation(
        capacity, variables, name=f"c_capacity_{agent}"
    )


def create_agent_hosting_constraint(
    agent: str,
    hosting_costs: Dict[str, float],
    agent_variables: Dict[str, BinaryVariable],
) -> Constraint:
    """Soft: hosting cost incurred by `agent` for the computations it
    accepts (reference :117-155)."""
    comps = sorted(agent_variables)
    variables = [agent_variables[c] for c in comps]
    costs = [hosting_costs[c] for c in comps]

    def hosting(*values):
        return sum(c * v for c, v in zip(costs, values))

    return NAryFunctionRelation(
        hosting, variables, name=f"c_hosting_{agent}"
    )


def create_agent_comp_comm_constraint(
    agent: str,
    computation: str,
    neighbor_agents: Dict[str, str],
    route: Callable[[str, str], float],
    comm_load: Callable[[str, str], float],
    variable: BinaryVariable,
) -> Constraint:
    """Soft: communication cost if `agent` hosts `computation`, summed
    over its neighbor computations' hosting agents (reference
    :158-199).

    neighbor_agents: neighbor computation -> agent currently hosting it.
    route(a, b): route cost between agents; comm_load(c, n): message
    load between the computation and neighbor n.
    """
    total = sum(
        route(agent, other) * comm_load(computation, neighbor)
        for neighbor, other in neighbor_agents.items()
    )

    def comm(value):
        return total * value

    return NAryFunctionRelation(
        comm, [variable], name=f"c_comm_{computation}_{agent}"
    )

"""Performance-regression gate for the MaxSum superstep.

Motivation (round-3 verdict): the bench's absolute CPU cycles/s drifted
927 -> 755 -> 665 across rounds.  Investigation showed the r1->r2 step
was a real feature cost (exact-parity send-suppression landed between
BENCH_r01 and r02) and the rest was machine load — the r1 tree re-run on
the r4 machine measures the same as the r4 tree.  An absolute wall-clock
budget would therefore false-alarm on load and miss nothing; instead the
live kernel races a FROZEN copy of itself (golden_maxsum_kernel.py) in
the same process and must stay within RATIO_TOL of it.  A future change
that slows the superstep >35% fails here regardless of machine speed.

The parity test doubles as a semantics freeze: the live kernel must
produce the golden kernel's exact trajectory (same values, same cycle
of convergence) so "optimizations" cannot silently change semantics.
"""

import time
from functools import partial

import jax
import numpy as np
import pytest

from tests.unit import golden_maxsum_kernel as golden

N_VARS = 2_000
N_COLORS = 3
CYCLES = 100
RATIO_TOL = 1.35
REPEATS = 5


@pytest.fixture(scope="module")
def problem():
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.engine.compile import compile_dcop

    rng = np.random.default_rng(11)
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP("perf_gc", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(N_VARS)]
    for v in variables:
        dcop.add_variable(v)
    eq = np.eye(N_COLORS, dtype=np.float64)
    seen = set()
    for k in range(int(N_VARS * 1.5)):
        i, j = rng.choice(N_VARS, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], eq, f"c{k}"))
    graph, meta = compile_dcop(dcop, noise_level=0.01)
    return jax.device_put(graph)


def _best_time(fn, graph):
    jax.block_until_ready(fn(graph))  # compile + warm
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(graph))
        best = min(best, time.perf_counter() - t0)
    return best


def test_superstep_not_slower_than_golden(problem):
    from pydcop_tpu.ops import maxsum as ops

    live = jax.jit(partial(
        ops.run_maxsum, max_cycles=CYCLES, stop_on_convergence=False))
    gold = jax.jit(partial(golden.run_maxsum, max_cycles=CYCLES))
    t_live = _best_time(live, problem)
    t_gold = _best_time(gold, problem)
    ratio = t_live / t_gold
    assert ratio <= RATIO_TOL, (
        f"live superstep is {ratio:.2f}x the frozen r4 baseline "
        f"({t_live*1e3:.2f} ms vs {t_gold*1e3:.2f} ms for {CYCLES} "
        f"cycles) — a real kernel regression, not machine noise "
        f"(both timed in this process)"
    )


def test_superstep_semantics_frozen(problem):
    from pydcop_tpu.ops import maxsum as ops

    live = jax.jit(partial(
        ops.run_maxsum, max_cycles=CYCLES, stop_on_convergence=False))
    gold = jax.jit(partial(golden.run_maxsum, max_cycles=CYCLES))
    s_live, v_live = live(problem)
    s_gold, v_gold = gold(problem)
    assert (np.asarray(v_live) == np.asarray(v_gold)).all()
    assert bool(s_live.stable) == bool(s_gold.stable)
    np.testing.assert_array_equal(
        np.asarray(s_live.f2v[0]), np.asarray(s_gold.f2v[0]))

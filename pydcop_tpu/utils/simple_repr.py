"""JSON-able object serialization — the wire format for messages and defs.

Reference parity: pydcop/utils/simple_repr.py:68 (``SimpleRepr`` mixin),
:133 (``simple_repr``), :175 (``from_repr``).

An object opts in by mixing in :class:`SimpleRepr`.  Its repr is a plain
dict ``{"__module__": ..., "__qualname__": ..., <arg>: <repr>...}`` where
the args are discovered from the ``__init__`` signature and read back from
attributes of the same name (``self.<arg>`` or ``self._<arg>``).  The
inverse, :func:`from_repr`, imports the class and calls ``__init__`` with
the decoded args.  This keeps every message / definition JSON- and
YAML-serializable without a schema registry.
"""

import importlib
import inspect
from typing import Any


class SimpleReprException(Exception):
    pass


class SimpleRepr:
    """Mixin providing automatic ``_simple_repr`` from the init signature.

    Subclasses whose init args do not map 1:1 to attributes may either set
    ``_repr_mapping = {arg_name: attr_name}`` or override ``_simple_repr``.
    """

    _repr_mapping: dict = {}

    def _simple_repr(self):
        r = {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
        }
        sig = inspect.signature(self.__init__)
        for name, param in sig.parameters.items():
            if name in ("self", "args", "kwargs"):
                continue
            attr = self._repr_mapping.get(name, name)
            if hasattr(self, attr):
                val = getattr(self, attr)
            elif hasattr(self, "_" + attr):
                val = getattr(self, "_" + attr)
            elif param.default is not inspect.Parameter.empty:
                val = param.default
            else:
                raise SimpleReprException(
                    f"Cannot build repr for {self!r}: no attribute for init "
                    f"argument {name!r} (tried {attr!r} and '_{attr}')"
                )
            r[name] = simple_repr(val)
        return r


def simple_repr(o: Any):
    """Return a JSON-able representation of `o` (recursively)."""
    if o is None or isinstance(o, (str, int, float, bool)):
        return o
    if isinstance(o, (list, tuple)):
        return [simple_repr(i) for i in o]
    if isinstance(o, set):
        return [simple_repr(i) for i in o]
    if isinstance(o, dict):
        return {k: simple_repr(v) for k, v in o.items()}
    if hasattr(o, "_simple_repr"):
        return o._simple_repr()
    raise SimpleReprException(
        f"Object {o!r} of type {type(o)} has no simple repr (missing "
        "SimpleRepr mixin?)"
    )


def from_repr(r: Any):
    """Rebuild an object from its simple repr (inverse of simple_repr)."""
    if r is None or isinstance(r, (str, int, float, bool)):
        return r
    if isinstance(r, list):
        return [from_repr(i) for i in r]
    if isinstance(r, dict):
        if "__module__" in r and "__qualname__" in r:
            module = importlib.import_module(r["__module__"])
            qualname = r["__qualname__"]
            cls = module
            for part in qualname.split("."):
                cls = getattr(cls, part)
            if hasattr(cls, "_from_repr"):
                args = {k: v for k, v in r.items() if not k.startswith("__")}
                return cls._from_repr(args)
            args = {
                k: from_repr(v) for k, v in r.items() if not k.startswith("__")
            }
            return cls(**args)
        return {k: from_repr(v) for k, v in r.items()}
    raise SimpleReprException(f"Cannot rebuild object from repr {r!r}")

"""Device engine: compiles computation graphs into dense padded arrays and
runs message-passing algorithms as jitted bulk-synchronous supersteps.

This is the TPU-native replacement for the reference's thread-per-agent
runtime (pydcop/infrastructure/agents.py): one BSP superstep = one XLA
step over *all* computations, batched by bucket, instead of one Python
thread per agent popping messages off a queue.
"""

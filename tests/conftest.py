"""Test configuration: force an 8-device virtual CPU platform so sharding
tests exercise real multi-device code paths without TPU hardware.

Note: this environment pre-imports jax (sitecustomize on PYTHONPATH) with
JAX_PLATFORMS=axon, so env vars alone are not enough — we must override
through jax.config before any backend is initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# CPU tests must not depend on the TPU tunnel: without this, every CLI
# subprocess re-registers the axon PJRT plugin and hangs if the tunnel
# is down (the pytest process itself registered at interpreter start,
# but jax_platforms=cpu below keeps it unused).
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-minute statistical tests (deselect with "
        "-m 'not slow'; they still run by default)",
    )

"""Layout A/B for the MaxSum superstep at scale: edge-major (current
engine default, messages [F, arity, D]) vs lane-major (factors on the
TPU lane axis, messages [D, arity, F] — ops/maxsum_lane.py), plus the
edge-major "sorted" aggregation for a third column.

Motivation (BENCH_TPU.md): past VMEM residency the superstep is
scatter/layout-bound (8.4 ms/cycle at 100k vars, ~0.5% of HBM peak on
a v5e), and an on-chip prototype of the transposed layout measured
1.7x/1.3x on the raw message math.  This harness measures the FULL
superstep per layout on the synthetic 3-coloring scale problem
(bench.bench_scale) at 10k / 100k / 1M vars, so the number that
decides the scale path's default is end-to-end, not op-level.

Run on the target backend:  python benchmarks/exp_layout.py
Prints one JSON line per size: ms/cycle per configuration + the
selected-assignment agreement between layouts at that size (the
layouts reassociate the per-variable float sums, so trajectories can
split on near-ties; agreement is reported, not asserted — the
bit-level contract is tests/unit/test_maxsum_lane.py).
"""

import json
import sys
import time

import numpy as np


def main():
    from pydcop_tpu.utils.cleanenv import ensure_live_backend

    ensure_live_backend(tag="exp_layout")
    import os

    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench as bench_mod

    # Compile frugality (round 5): every (config, size, scan-length)
    # is a distinct XLA program costing minutes of remote compile
    # through the axon tunnel.  The 10k row is dropped (VMEM-resident
    # regime, already decided by the headline bench) and edge_sorted
    # is dropped (exp_aggregation measured sorted ~= scatter on-chip
    # at 100k: 5.22 vs 4.94 ms/iter) — the decision this harness
    # feeds is edge-major vs lane-major in the HBM-bound regime.
    configs = [
        ("edge_scatter", {"aggregation": "scatter", "layout": "edge"}),
        ("lane", {"aggregation": "scatter", "layout": "lane"}),
    ]
    for n_vars in (100_000, 1_000_000):
        cycles = 200 if n_vars <= 100_000 else 50
        out = {"n_vars": n_vars, "cycles": cycles,
               "backend": jax.devices()[0].platform}
        values = {}
        for name, kw in configs:
            t0 = time.perf_counter()
            cps, graph, vals = bench_mod.bench_scale(
                n_vars=n_vars, cycles=cycles, return_values=True, **kw)
            out[f"{name}_ms_per_cycle"] = (
                round(1e3 / cps, 4) if cps else None)
            out[f"{name}_total_s"] = round(time.perf_counter() - t0, 1)
            # Agreement column reuses the timed run's own assignment —
            # no extra solve in the scarce on-chip window.
            values[name] = vals
            del graph
        if "edge_scatter" in values and "lane" in values:
            agree = float(np.mean(
                values["edge_scatter"] == values["lane"]))
            out["lane_vs_edge_assignment_agreement"] = round(agree, 4)
        print(json.dumps(out))
        sys.stdout.flush()


if __name__ == "__main__":
    main()

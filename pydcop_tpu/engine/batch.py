"""Batched multi-instance solving: many DCOPs in ONE XLA program.

A capability the reference architecture cannot express: its benchmark
sweeps (`pydcop batch`) run one subprocess per instance
(pydcop/commands/batch.py), paying process + solve overhead per run.
On device, same-shaped compiled graphs stack into batched arrays and
`jax.vmap` turns the whole MaxSum solve into a single program over the
instance axis — N problems cost barely more than one (the MXU/VPU work
batches; the host launches once).

Shape contract: every instance must compile to identical array shapes
(same variable count, same dmax, same bucket layout) — exactly what
seeded generator sweeps produce (same config, different seeds or cost
tables).  A shape mismatch raises instead of silently padding, so the
caller controls the batching granularity.
"""

import functools
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import (
    CompiledFactorGraph,
    FactorGraphMeta,
    compile_dcop,
)
from pydcop_tpu.ops import maxsum as maxsum_ops


def _stack_graphs(
    graphs: Sequence[CompiledFactorGraph],
) -> CompiledFactorGraph:
    shapes = [
        (g.var_costs.shape,) + tuple(b.costs.shape for b in g.buckets)
        for g in graphs
    ]
    if any(s != shapes[0] for s in shapes):
        raise ValueError(
            "Batched solving requires identical compiled shapes; got "
            f"{sorted(set(shapes))}"
        )
    return jax.tree.map(lambda *xs: jnp.stack(xs), *graphs)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_cycles", "damping", "damp_vars", "damp_factors",
        "stability",
    ),
)
def _batched_solve(stacked, *, max_cycles, damping, damp_vars,
                   damp_factors, stability):
    """One jitted program per solver-parameter combination (jit's own
    cache keys on the static args), reused across calls — a fresh
    closure per call would retrace and recompile every time."""

    def solve_one(graph):
        state, values = maxsum_ops.run_maxsum(
            graph, max_cycles,
            damping=damping,
            damp_vars=damp_vars,
            damp_factors=damp_factors,
            stability=stability,
            stop_on_convergence=False,
        )
        return values, state.cycle

    return jax.vmap(solve_one)(stacked)


def solve_maxsum_batch(
    dcops: Sequence[DCOP],
    max_cycles: int = 200,
    noise_level: float = 0.01,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
) -> List[Dict]:
    """Solve a batch of same-shaped DCOPs in one vmapped program.

    Returns one dict per instance: assignment, cost (host-evaluated),
    cycles.  All instances run ``max_cycles`` cycles (no convergence
    stop: a data-dependent loop bound would serialize the batch).
    """
    if not dcops:
        return []
    # Same-structured instances (same graph, different cost tables —
    # the repeated-traffic serving pattern) are exactly what the
    # structure-keyed compile cache serves: instance 1 builds the
    # layout/agg arrays, instances 2..N reuse them
    # (engine/compile.CompileCache), matching the device side where
    # vmap already made N solves cost barely more than one.
    compiled: List[Tuple[CompiledFactorGraph, FactorGraphMeta]] = [
        compile_dcop(d, noise_level=noise_level) for d in dcops
    ]
    graphs = [c[0] for c in compiled]
    metas = [c[1] for c in compiled]
    stacked = _stack_graphs(graphs)

    values, cycles = _batched_solve(
        stacked,
        max_cycles=max_cycles,
        damping=damping,
        damp_vars=damping_nodes in ("vars", "both"),
        damp_factors=damping_nodes in ("factors", "both"),
        stability=stability,
    )
    values = np.asarray(jax.device_get(values))
    cycles = np.asarray(jax.device_get(cycles))

    results = []
    for i, (dcop, meta) in enumerate(zip(dcops, metas)):
        assignment = meta.assignment_from_indices(values[i])
        cost, violations = dcop.solution_cost(assignment)
        results.append({
            "assignment": assignment,
            "cost": cost,
            "violations": violations,
            "cycles": int(cycles[i]),
        })
    return results

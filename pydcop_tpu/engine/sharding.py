"""Mesh construction and sharding specs for the device engine.

The sharding story (scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):

- one mesh axis ``"shard"`` over all devices;
- factor buckets (costs + var_ids) and their message arrays are sharded
  on the leading factor axis;
- variable tables ([V+1, D] costs/valid/beliefs) are replicated;
- the per-superstep segment-sum over sharded messages into replicated
  totals is the only collective XLA needs to insert (an all-reduce over
  ICI) — everything else is local.

This replaces the reference's distribution-of-computations-over-agents as
the *intra-pod* scaling mechanism (reference: pydcop/distribution/);
the distribution algorithms remain for agent-mode and for balancing
which factors land on which shard.
"""

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pydcop_tpu.engine.compile import CompiledFactorGraph, FactorBucket

SHARD_AXIS = "shard"


def make_mesh(n_devices: Optional[int] = None,
              devices=None) -> Mesh:
    """A 1-D mesh over (the first n of) the available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_graph(graph: CompiledFactorGraph,
                mesh: Mesh) -> CompiledFactorGraph:
    """Place the compiled graph on the mesh: buckets sharded on the
    factor axis, variable tables replicated.

    Bucket rows must be padded to a multiple of the mesh size (use
    ``pad_to=mesh.size`` when compiling).
    """
    replicated = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P(SHARD_AXIS))
    buckets = []
    for b in graph.buckets:
        if b.costs.shape[0] % mesh.size:
            raise ValueError(
                f"Bucket with {b.costs.shape[0]} rows not divisible by "
                f"mesh size {mesh.size}; compile with pad_to=mesh.size"
            )
        buckets.append(FactorBucket(
            costs=jax.device_put(b.costs, row_sharded),
            var_ids=jax.device_put(b.var_ids, row_sharded),
        ))
    return CompiledFactorGraph(
        var_costs=jax.device_put(graph.var_costs, replicated),
        var_valid=jax.device_put(graph.var_valid, replicated),
        buckets=tuple(buckets),
    )

"""``pydcop profile``: the where-the-time-went analyzer.

``pydcop profile report`` answers the efficiency question the raw
artifacts only hint at: of every second of wall clock, how much was
useful device work vs. padding, compile, queue wait and host glue —
and on which backend?  Two modes over the same report shape:

- ``--url http://HOST:PORT`` asks a RUNNING process (a ``pydcop
  serve`` front end or any ``--serve_metrics`` solve) for its live
  efficiency rollup over ``GET /profile`` (observability/efficiency.py
  — request time ledgers, per-structure attainment, waste by cause);
- offline, over artifacts: ``--trace FILE...`` aggregates an exported
  trace's spans into the time breakdown (``serve_queued`` /
  ``serve_dispatch`` / ``engine_segment`` / ``jit_compile`` — the
  span taxonomy maps onto the ledger components), ``--metrics
  FILE.jsonl`` reads the last registry snapshot's ledger counters,
  and ``--bench DIR`` adds the per-leg resolved-backend table from
  ``BENCH_r*.json`` ``leg_backends`` (backend honesty: which legs
  actually ran on the accelerator).

Output: a where-the-time-went breakdown (component seconds + share),
the top-N structures by device time, waste by cause (padding vs
compile vs queue), and the resolved-backend line; ``--json`` emits
the full document for tooling.  docs/observability.md "Efficiency
accounting" documents the fields.
"""

import glob as glob_mod
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

# Trace span name -> ledger-ish component for the offline breakdown.
# Spans overlap (engine_segment nests inside serve_dispatch), so the
# offline table reports each row as itself rather than forcing the
# disjoint ledger taxonomy — the mapping only orders/annotates them.
SPAN_COMPONENTS = (
    ("serve_submit", "submit"),
    ("serve_queued", "queue"),
    ("serve_dispatch", "dispatch (incl. engine)"),
    ("engine_segment", "device execute"),
    ("jit_compile", "cold compile"),
    ("engine_call", "device execute (warm)"),
    ("session_segment", "session segment"),
    ("session_events", "session events"),
    ("checkpoint_write", "checkpointing"),
)


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "profile",
        help="device-efficiency analysis (where the time went)")
    profile_sub = parser.add_subparsers(
        title="profile commands", dest="profile_command")

    report = profile_sub.add_parser(
        "report",
        help="where-the-time-went breakdown, attainment, waste by "
             "cause")
    report.add_argument(
        "--url", default=None, metavar="URL",
        help="telemetry endpoint of a running process (e.g. "
             "http://127.0.0.1:8080): reads its live GET /profile "
             "rollup")
    report.add_argument(
        "--trace", nargs="*", default=None, metavar="FILE",
        help="exported trace file(s) (chrome or jsonl): offline span "
             "aggregation")
    report.add_argument(
        "--metrics", default=None, metavar="FILE",
        help="metrics snapshot JSONL (--metrics runs): ledger "
             "counters from the last snapshot")
    report.add_argument(
        "--bench", default=None, metavar="DIR",
        help="bench history directory (BENCH_r*.json): per-leg "
             "resolved-backend table")
    report.add_argument(
        "--top", type=int, default=10,
        help="structures to list by device time (default 10)")
    report.add_argument(
        "--timeout", type=float, default=10.0,
        help="HTTP timeout for --url (seconds, default 10)")
    report.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the full report as JSON")
    report.set_defaults(func=run_report)

    parser.set_defaults(func=_no_subcommand(parser))


def _no_subcommand(parser):
    def run(_args) -> int:
        parser.print_help(sys.stderr)
        return 2

    return run


# ------------------------------------------------------------------ #
# collectors
# ------------------------------------------------------------------ #

def fetch_live(url: str, timeout: float) -> Dict[str, Any]:
    from urllib.request import urlopen

    endpoint = url.rstrip("/") + "/profile"
    with urlopen(endpoint, timeout=timeout) as resp:  # noqa: S310
        return json.loads(resp.read())


def trace_breakdown(paths: List[str],
                    top: int = 10) -> Dict[str, Any]:
    """Offline where-the-time-went from exported trace spans: the
    known request/engine span families in taxonomy order, plus the
    top structures by ``engine_segment``/``serve_dispatch`` time
    (grouped by the bin/batch labels the spans already carry)."""
    from pydcop_tpu.observability.trace import (
        load_trace_file,
        summarize_spans,
    )

    events: List[Dict[str, Any]] = []
    for path in paths:
        events.extend(load_trace_file(path))
    rows = {r["name"]: r for r in summarize_spans(events)}
    components = []
    for span, label in SPAN_COMPONENTS:
        row = rows.get(span)
        if row is None:
            continue
        components.append({
            "span": span, "component": label,
            "count": row["count"],
            "total_ms": round(row["total_ms"], 3),
            "mean_ms": round(row["mean_ms"], 3),
        })
    # Structure attribution: serve_dispatch spans carry their bin
    # label, engine_segment spans their batch shape args.
    by_structure: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X" or ev.get("name") != "serve_dispatch":
            continue
        label = (ev.get("args") or {}).get("bin") or "?"
        by_structure.setdefault(label, [0, 0.0])
        by_structure[label][0] += 1
        by_structure[label][1] += float(ev.get("dur", 0.0)) / 1000.0
    structures = [
        {"structure": label, "dispatches": int(count),
         "total_ms": round(total, 3)}
        for label, (count, total) in by_structure.items()
    ]
    structures.sort(key=lambda r: -r["total_ms"])
    other = [
        {"span": r["name"], "count": r["count"],
         "total_ms": round(r["total_ms"], 3)}
        for r in summarize_spans(events, top=top)
        if r["name"] not in {s for s, _label in SPAN_COMPONENTS}
    ]
    return {
        "events": len(events),
        "components": components,
        "structures": structures[:top],
        "other_spans": other,
    }


def metrics_breakdown(path: str) -> Dict[str, Any]:
    """Ledger/efficiency series out of the LAST snapshot line of a
    metrics JSONL file (snapshots are cumulative, so the last line is
    the run's total)."""
    last: Optional[Dict[str, Any]] = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                last = json.loads(line)
            except ValueError:
                continue
    if not last:
        return {"error": f"no snapshot rows in {path}"}
    metrics = last.get("metrics") or {}
    out: Dict[str, Any] = {"snapshot_ts": last.get("ts")}
    ledger = metrics.get("pydcop_request_ledger_seconds_total")
    if ledger:
        out["ledger_components_s"] = {
            s["labels"].get("component", "?"): round(s["value"], 6)
            for s in ledger.get("samples", [])
        }
    for name, key in (
        ("pydcop_useful_work_fraction", "useful_work_fraction"),
        ("pydcop_efficiency_attainment", "attainment"),
        ("pydcop_device_execute_seconds_total", "device_execute_s"),
        ("pydcop_device_compile_seconds_total", "device_compile_s"),
    ):
        series = metrics.get(name)
        if series:
            out[key] = {
                ",".join(f"{k}={v}" for k, v in sorted(
                    s["labels"].items())) or "all": round(
                        s["value"], 6)
                for s in series.get("samples", [])
            }
    return out


def bench_backends(root: str) -> List[Dict[str, Any]]:
    """Per-leg resolved-backend table from the bench history's
    ``leg_backends`` keys (absent before PR 11 — older rounds report
    only their headline backend)."""
    rows: List[Dict[str, Any]] = []
    numbered = []
    for path in glob_mod.glob(os.path.join(root, "BENCH_r*.json")):
        match = re.fullmatch(r"BENCH_r(\d+)\.json",
                             os.path.basename(path))
        if match:
            numbered.append((int(match.group(1)), path))
    for _, path in sorted(numbered):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed") or {}
        rows.append({
            "source": os.path.basename(path),
            "backend": parsed.get("backend") or "cpu",
            "leg_backends": {
                leg: info.get("backend")
                for leg, info in (
                    parsed.get("leg_backends") or {}).items()
                if isinstance(info, dict)
            },
        })
    return rows


# ------------------------------------------------------------------ #
# rendering
# ------------------------------------------------------------------ #

def _pct(part: float, whole: float) -> str:
    return f"{part / whole:6.1%}" if whole > 0 else "     -"


def render_live(doc: Dict[str, Any], out) -> None:
    backend = doc.get("backend") or {}
    probe = ("ok" if backend.get("probe_ok")
             else f"{backend.get('probe_failures', '?')} failure(s)")
    print(f"backend: {backend.get('backend', '?')} "
          f"({backend.get('n_devices', '?')} device(s), "
          f"accelerator probe {probe})", file=out)
    ledger = doc.get("ledger") or {}
    components = ledger.get("components_s") or {}
    total = ledger.get("total_s") or 0.0
    if components:
        print("\nwhere the time went (request ledgers):", file=out)
        for name, secs in sorted(components.items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {name:<10} {secs:10.3f}s "
                  f"{_pct(secs, total)}", file=out)
        print(f"  {'total':<10} {total:10.3f}s over "
              f"{ledger.get('counts', {})}", file=out)
    waste = doc.get("waste_by_cause") or {}
    if waste:
        print("\nwaste by cause:", file=out)
        for name, secs in sorted(waste.items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {name:<12} {secs:10.3f}s", file=out)
    for backend_name, agg in (doc.get("backends") or {}).items():
        att = agg.get("attainment")
        useful = agg.get("useful_work_fraction")
        print(f"\n[{backend_name}] execute {agg.get('execute_s', 0):.3f}s "
              f"over {agg.get('dispatches', 0)} dispatch(es), "
              f"attainment "
              f"{att if att is not None else 'n/a (no cost entries)'}"
              f", useful_work_fraction "
              f"{useful if useful is not None else 'n/a'} "
              f"(peak: {agg.get('peak_source', '?')})", file=out)
    structures = doc.get("structures") or []
    if structures:
        print("\ntop structures by device time:", file=out)
        for row in structures:
            att = row.get("attainment")
            print(f"  {row['structure']:<28} [{row['backend']}] "
                  f"{row['device_s']:8.3f}s "
                  f"{row['dispatches']:4d} dispatch(es) "
                  f"attainment "
                  f"{att if att is not None else 'n/a'}", file=out)


def render_trace(doc: Dict[str, Any], out) -> None:
    print(f"trace: {doc.get('events', 0)} event(s)", file=out)
    components = doc.get("components") or []
    if components:
        print("\nwhere the time went (spans; nested spans overlap):",
              file=out)
        for c in components:
            print(f"  {c['component']:<24} ({c['span']}) "
                  f"{c['total_ms']:10.3f}ms x{c['count']}", file=out)
    structures = doc.get("structures") or []
    if structures:
        print("\ntop bins by dispatch time:", file=out)
        for row in structures:
            print(f"  {row['structure']:<28} {row['total_ms']:10.3f}ms "
                  f"x{row['dispatches']}", file=out)


def run_report(args) -> int:
    report: Dict[str, Any] = {"mode": []}
    if args.url:
        try:
            report["live"] = fetch_live(args.url, args.timeout)
            report["mode"].append("live")
        except Exception as exc:  # noqa: BLE001 — CLI surface
            print(f"pydcop profile: could not fetch {args.url}"
                  f"/profile: {exc}", file=sys.stderr)
            return 2
    if args.trace:
        try:
            report["trace"] = trace_breakdown(args.trace,
                                              top=args.top)
            report["mode"].append("trace")
        except Exception as exc:  # noqa: BLE001
            print(f"pydcop profile: could not read trace(s): {exc}",
                  file=sys.stderr)
            return 2
    if args.metrics:
        try:
            report["metrics"] = metrics_breakdown(args.metrics)
        except Exception as exc:  # noqa: BLE001 — CLI surface
            print(f"pydcop profile: could not read metrics file "
                  f"{args.metrics}: {exc}", file=sys.stderr)
            return 2
        report["mode"].append("metrics")
    if args.bench:
        report["bench_backends"] = bench_backends(args.bench)
        report["mode"].append("bench")
    if not report["mode"]:
        # No source named: report on THIS process's tracker (mostly a
        # plumbing self-test, like `pydcop debug bundle` without
        # --url) so the command always answers.
        from pydcop_tpu.observability.efficiency import tracker

        report["live"] = tracker.rollup(top_n=args.top)
        report["mode"].append("self")
    if args.as_json:
        print(json.dumps(report, default=str))
        return 0
    out = sys.stdout
    if "live" in report:
        render_live(report["live"], out)
    if "trace" in report:
        if "live" in report:
            print("", file=out)
        render_trace(report["trace"], out)
    if "metrics" in report:
        print(f"\nmetrics snapshot: "
              f"{json.dumps(report['metrics'], default=str)}",
              file=out)
    if "bench_backends" in report:
        print("\nbench legs by resolved backend:", file=out)
        for row in report["bench_backends"]:
            legs = (", ".join(f"{leg}={b}" for leg, b in
                              sorted(row["leg_backends"].items()))
                    or f"(pre-leg_backends: {row['backend']})")
            print(f"  {row['source']:<16} {legs}", file=out)
    return 0

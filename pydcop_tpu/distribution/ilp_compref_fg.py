"""ilp_compref_fg: ilp_compref applied to factor graphs.

Reference parity: pydcop/distribution/ilp_compref_fg.py — the placement
model is graph-agnostic; factor graphs simply contribute more
computations (variables and factors).
"""

from pydcop_tpu.distribution.ilp_compref import (  # noqa: F401
    distribute,
    distribution_cost,
)

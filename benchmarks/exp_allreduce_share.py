"""Per-superstep all-reduce share in the sharded engine (VERDICT r3
item 7).

The sharded superstep's one cross-device op is the variable
aggregation: factor buckets are sharded on rows, the [V+1, D] belief
totals are replicated, so XLA inserts an all-reduce (psum) of the full
table every superstep (engine/sharding.py).  This experiment answers
"how much of the superstep is that collective" two ways:

1. MODELED for a v5e-8 mesh (ICI 2D torus): a ring all-reduce moves
   2(N-1)/N * V*D*4 bytes per link; local work streams the shard's
   buckets from HBM.  The model compares ICI time vs HBM time per
   superstep — this is the number that answers the question for the
   real chip, and it is valid regardless of where this script runs.
2. MEASURED on whatever mesh is available (the 8-device virtual CPU
   mesh in CI, a real slice when run there): per-superstep wall time
   single-device vs sharded.  The sharded-vs-single ratio shows
   whether the collective+partitioning overhead beats the N-way
   compute split on that backend; the per-op attribution of the
   collective itself comes from the model (XLA offers no per-op
   timer here short of a full profile trace).

Prints one JSON line.
"""

import json
import os
import sys
from functools import partial

import numpy as np


V5E_ICI_BYTES_PER_S_PER_LINK = 45e9   # public v5e spec, per direction
V5E_HBM_BYTES_PER_S = 819e9


def modeled_share(n_vars, n_edges, d, n_dev):
    """v5e-8 analytical breakdown for one superstep."""
    table_bytes = (n_vars + 1) * d * 4
    allreduce_bytes = 2 * (n_dev - 1) / n_dev * table_bytes
    ici_s = allreduce_bytes / V5E_ICI_BYTES_PER_S_PER_LINK
    # Local traffic per device: messages (2 passes: factor update,
    # suppress), costs, counts — ~6 arrays of [E/N, 2, D] plus the
    # belief table; use the roofline counter for the real number.
    local_bytes = (
        6 * (n_edges / n_dev) * 2 * d * 4 + 2 * table_bytes
    )
    hbm_s = local_bytes / V5E_HBM_BYTES_PER_S
    return {
        "modeled_allreduce_bytes": int(allreduce_bytes),
        "modeled_ici_s": ici_s,
        "modeled_local_hbm_s": hbm_s,
        "modeled_allreduce_share": round(
            ici_s / (ici_s + hbm_s), 3),
    }


def main():
    from pydcop_tpu.utils.cleanenv import ensure_live_backend

    ensure_live_backend(tag="exp_allreduce_share")
    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench as bench_mod
    from pydcop_tpu.engine.sharding import make_mesh, shard_graph
    from pydcop_tpu.ops import maxsum as ops

    from pydcop_tpu.engine.timing import warmed_marginal

    n_vars = 1_000_000
    d = 3
    # Differencing bounds (engine/timing.py): block_until_ready is a
    # partial sync on the axon tunnel with a fixed ~130 ms round-trip
    # that a naive min-of-3 would report as superstep time.
    cyc_lo, cyc_hi = 10, 60
    n_dev = len(jax.devices())

    # Build once (scatter aggregation — the sharded path's only
    # option), then re-pad for the mesh.
    _, graph = bench_mod.bench_scale(n_vars=n_vars, cycles=1)
    n_edges = graph.buckets[0].var_ids.shape[0]

    def timeit(g):
        per_cycle, _, _ = warmed_marginal(
            lambda c: jax.jit(partial(ops.run_maxsum, max_cycles=c,
                                      stop_on_convergence=False)),
            cyc_lo, cyc_hi, args=(g,), reps=3)
        return per_cycle * 1e3  # ms / superstep

    single_ms = timeit(graph)
    out = {
        "experiment": "allreduce_share",
        "backend": jax.devices()[0].platform,
        "n_vars": n_vars, "n_edges": int(n_edges), "n_devices": n_dev,
        "single_ms_per_cycle": round(single_ms, 3),
        **modeled_share(n_vars, n_edges, d, 8),
    }
    if n_dev > 1:
        mesh = make_mesh(n_dev)
        # Row-pad the bucket to the mesh size.
        b = graph.buckets[0]
        pad = (-b.var_ids.shape[0]) % n_dev
        if pad:
            costs = np.concatenate(
                [np.asarray(b.costs),
                 np.zeros((pad,) + b.costs.shape[1:], np.float32)])
            ids = np.concatenate(
                [np.asarray(b.var_ids),
                 np.full((pad, 2), n_vars, np.int32)])
            graph = graph._replace(
                buckets=(type(b)(costs, ids),))
        sharded = shard_graph(
            jax.device_get(graph), mesh)
        out["sharded_ms_per_cycle"] = round(timeit(sharded), 3)
        out["sharded_vs_single"] = round(
            out["sharded_ms_per_cycle"] / single_ms, 2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

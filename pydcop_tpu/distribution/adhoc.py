"""adhoc distribution: capacity-aware heuristic honoring hints.

Reference parity: pydcop/distribution/adhoc.py (distribute :56,
IJCAI-16): must_host hints placed first, host_with groups co-located,
remaining computations placed next to their neighbors under capacity.
"""

from pydcop_tpu.distribution._base import (
    distribution_cost_impl,
    greedy_place,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None, **_):
    return greedy_place(
        computation_graph, agentsdef, hints,
        computation_memory, communication_load,
        hosting_weight=0.0,
    )


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return distribution_cost_impl(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load, ratio=1.0)

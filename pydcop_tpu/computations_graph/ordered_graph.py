"""Constraints hypergraph + a total (lexical) variable order.

Reference parity: pydcop/computations_graph/ordered_graph.py (OrderLink
:119 with next/previous, build_computation_graph :182).  Used by: syncbb.
"""

from typing import Iterable, List, Optional

from pydcop_tpu.computations_graph.objects import (
    ComputationGraph,
    ComputationNode,
    Link,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import Constraint


class OrderLink(Link):
    """Directed next/previous link in the total order."""

    def __init__(self, link_type: str, source: str, target: str):
        if link_type not in ("next", "previous"):
            raise ValueError(f"Invalid order link type {link_type}")
        super().__init__([source, target], link_type)
        self._source = source
        self._target = target

    @property
    def source(self) -> str:
        return self._source

    @property
    def target(self) -> str:
        return self._target

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "link_type": self.type,
            "source": self._source,
            "target": self._target,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["link_type"], r["source"], r["target"])


class OrderedVarNode(ComputationNode):
    def __init__(self, variable: Variable,
                 constraints: Iterable[Constraint],
                 links: Iterable[OrderLink]):
        super().__init__(variable.name, "OrderedVariableComputation", links)
        self._variable = variable
        self._constraints = list(constraints)

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        return list(self._constraints)

    @property
    def next_node(self) -> Optional[str]:
        for l in self.links:
            if l.type == "next" and l.source == self.name:
                return l.target
        return None

    @property
    def previous_node(self) -> Optional[str]:
        for l in self.links:
            if l.type == "previous" and l.source == self.name:
                return l.target
        return None


class OrderedConstraintGraph(ComputationGraph):
    def __init__(self, nodes: Iterable[OrderedVarNode]):
        super().__init__("ordered_graph", nodes)

    @property
    def ordered_nodes(self) -> List[OrderedVarNode]:
        return sorted(self.nodes, key=lambda n: n.name)


def build_computation_graph(
        dcop: Optional[DCOP] = None,
        variables: Optional[Iterable[Variable]] = None,
        constraints: Optional[Iterable[Constraint]] = None,
) -> OrderedConstraintGraph:
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    ordered = sorted(variables, key=lambda v: v.name)
    nodes = []
    for i, v in enumerate(ordered):
        links = []
        if i > 0:
            links.append(OrderLink("previous", v.name, ordered[i - 1].name))
        if i < len(ordered) - 1:
            links.append(OrderLink("next", v.name, ordered[i + 1].name))
        v_constraints = [
            c for c in constraints
            if v.name in (d.name for d in c.dimensions)
        ]
        nodes.append(OrderedVarNode(v, v_constraints, links))
    return OrderedConstraintGraph(nodes)


def computation_memory(node: ComputationNode) -> float:
    if not isinstance(node, OrderedVarNode):
        raise TypeError(f"Unsupported node {node}")
    neighbors = set()
    for c in node.constraints:
        neighbors.update(
            v.name for v in c.dimensions if v.name != node.name
        )
    return len(neighbors)


def communication_load(src: ComputationNode, target: str) -> float:
    # SyncBB messages carry the current path: one (value, cost) per var.
    return 1

"""Agent lifecycle battery (reference scope:
tests/unit/test_infra_agents.py:107-351 — behaviors re-derived from the
runtime contract): add/remove computations around start, run-by-name,
pause fan-out, double-start."""

import time

import pytest

from pydcop_tpu.infrastructure.agents import Agent, AgentException
from pydcop_tpu.infrastructure.communication import (
    InProcessCommunicationLayer,
)
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
)


class Recorder(MessagePassingComputation):
    def __init__(self, name):
        super().__init__(name)
        self.started = False

    def on_start(self):
        self.started = True


def _agent(name="a1"):
    return Agent(name, InProcessCommunicationLayer())


def test_add_computation_before_start():
    agent = _agent()
    comp = Recorder("c1")
    agent.add_computation(comp)
    assert "c1" in [c.name for c in agent.computations]
    agent.start()
    try:
        agent.run()
        time.sleep(0.1)
        assert comp.started
    finally:
        agent.stop()


def test_add_computation_after_start():
    agent = _agent()
    agent.start()
    try:
        comp = Recorder("c1")
        agent.add_computation(comp)
        assert "c1" in [c.name for c in agent.computations]
        agent.run(["c1"])
        time.sleep(0.1)
        assert comp.started
    finally:
        agent.stop()


def test_run_computation_by_name_only_starts_named():
    agent = _agent()
    c1, c2 = Recorder("c1"), Recorder("c2")
    agent.add_computation(c1)
    agent.add_computation(c2)
    agent.start()
    try:
        agent.run(["c1"])
        time.sleep(0.1)
        assert c1.started and c1.is_running
        assert not c2.started
    finally:
        agent.stop()


def test_remove_running_computation():
    agent = _agent()
    comp = Recorder("c1")
    agent.add_computation(comp)
    agent.start()
    try:
        agent.run()
        time.sleep(0.1)
        agent.remove_computation("c1")
        assert "c1" not in [c.name for c in agent.computations]
        assert not comp.is_running
    finally:
        agent.stop()


def test_pause_several_computations():
    agent = _agent()
    comps = [Recorder(f"c{i}") for i in range(3)]
    for c in comps:
        agent.add_computation(c)
    agent.start()
    try:
        agent.run()
        time.sleep(0.1)
        for c in comps:
            c.pause(True)
        assert all(c.is_paused for c in comps)
        for c in comps:
            c.pause(False)
        assert not any(c.is_paused for c in comps)
    finally:
        agent.stop()


def test_double_start_raises():
    agent = _agent()
    agent.start()
    try:
        with pytest.raises(AgentException):
            agent.start()
    finally:
        agent.stop()


def test_computation_accessor_unknown_raises():
    agent = _agent()
    with pytest.raises(Exception):
        agent.computation("nope")

"""Structure-signature binning for the solve service.

A batched dispatch (engine/batch.run_stacked) requires every instance
in the stack to compile to identical array shapes, and the service
additionally promises that two *different* problem structures never
share a dispatch (same shapes with different scopes would vmap fine
mathematically, but one misrouted meta would decode the wrong
variables — the bin key keeps the invariant structural, not just
dimensional).  The key is the serving-side analogue of the PR-3
structure cache key (engine/compile.CompileCache): variable count,
domain padding, per-bucket shapes and the exact scope-index bytes.

Solver parameters ride in the key too: ``max_cycles``/``damping``/
``stability`` are static arguments of the jitted batched program, so
requests with different parameters can never share one dispatch.

**Envelope tier (ISSUE 11).**  Structure binning is exact — and
therefore degenerates to batch-size-1 under *diverse* traffic: two
different topologies never share a dispatch, so a zipf-distributed
request stream gets no batching at all.  :func:`envelope_key` is the
second, coarser tier above :func:`structure_signature`: it rounds a
graph's shape dimensions (variable count / domain / per-arity bucket
rows) up a small ladder of **shape envelopes**, so different-structure
problems that fit the same envelope can be mask-padded to identical
shapes (engine/batch.pad_graph_to_envelope — the PR-7 sentinel-row
autopad pattern) and dispatched as ONE vmapped program with results
bit-identical to solo solves.  The ladder is powers-of-two-ish so the
number of compiled envelope programs stays logarithmic in the traffic's
shape spread.  :func:`pack_decision` is the scheduler's per-flush cost
model: envelope packing is wasted-work-vs-dispatch-overhead arbitrage,
so it only happens when the modeled win beats solo dispatch.
"""

import hashlib
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

from pydcop_tpu.engine.compile import CompiledFactorGraph

# Solver parameters that are static in the batched program — the
# params half of the bin key, in canonical order.  ``prune`` rides in
# the key because the pruned and dense batched programs are different
# executables (same results — pruning never changes values).
PARAM_KEYS = ("max_cycles", "damping", "damping_nodes", "stability",
              "noise", "prune", "algo")

DEFAULT_PARAMS: Dict[str, Any] = {
    "max_cycles": 200,
    "damping": 0.5,
    "damping_nodes": "both",
    "stability": 0.1,
    "noise": 0.01,
    # 0 = dense, 1 = branch-and-bound pruning, "auto" = replay the
    # portfolio racer's cached decision for this structure (resolved
    # to 0/1 at submit, AFTER the graph compiles — never measured on
    # the serving path).
    "prune": 0,
    # "maxsum" = the iterative batched engine; "dpop" = exact
    # inference (ISSUE 17): results carry ``optimal: true``, width is
    # checked AT SUBMIT against ops/dpop.MAX_NODE_ELEMENTS (CEC
    # shrinkage included) and an over-wide problem is a structured 400
    # ``rejected_width``, never a dispatch-time 500.  Rides the bin
    # key, so dpop traffic never shares a dispatch with maxsum.
    "algo": "maxsum",
}


DAMPING_NODES = ("vars", "factors", "both", "none")

SERVING_ALGOS = ("maxsum", "dpop")


def normalize_params(overrides: Dict[str, Any] = None) -> Dict[str, Any]:
    """Fill a request's solver-parameter dict from the service
    defaults, rejecting unknown keys (a typo'd parameter silently
    falling back to a default would be a debugging trap) and
    canonicalizing every value's type — the values land in a hashable
    bin key AND in the jitted program's static arguments, so an
    unhashable or wrong-typed value must fail the submit (a 400), not
    the scheduler thread."""
    params = dict(DEFAULT_PARAMS)
    for key, value in (overrides or {}).items():
        if key not in DEFAULT_PARAMS:
            raise ValueError(
                f"unknown solver parameter {key!r}; valid: "
                f"{', '.join(PARAM_KEYS)}"
            )
        params[key] = value
    try:
        params["max_cycles"] = int(params["max_cycles"])
        for key in ("damping", "stability", "noise"):
            params[key] = float(params[key])
    except (TypeError, ValueError) as exc:
        raise ValueError(f"bad solver parameter value: {exc}")
    if params["prune"] != "auto":
        try:
            params["prune"] = int(params["prune"])
        except (TypeError, ValueError):
            params["prune"] = -1  # falls through to the check below
    if params["prune"] not in (0, 1, "auto"):
        raise ValueError(
            f"prune must be 0, 1 or 'auto', got "
            f"{(overrides or {}).get('prune')!r}")
    if params["damping_nodes"] not in DAMPING_NODES:
        raise ValueError(
            f"damping_nodes must be one of {DAMPING_NODES}, got "
            f"{params['damping_nodes']!r}")
    if params["algo"] not in SERVING_ALGOS:
        raise ValueError(
            f"algo must be one of {SERVING_ALGOS}, got "
            f"{params['algo']!r}")
    return params


def structure_signature(graph: CompiledFactorGraph) -> Tuple:
    """Hashable structural identity of a compiled graph.

    Shapes alone define *stackability*; the scope-index bytes make the
    signature injective over topologies, which is what "two structures
    never share a dispatch" needs.  Cost tables are deliberately NOT
    in the signature — same-structure requests with different costs
    are exactly the traffic that should coalesce.
    """
    return (
        graph.var_costs.shape,
        tuple(
            (b.costs.shape, b.var_ids.tobytes()) for b in graph.buckets
        ),
        # Aggregation layout arrays change the compiled program shape.
        tuple(
            None if a is None else a.shape
            for a in (graph.agg_perm, graph.agg_sorted_seg,
                      graph.agg_starts, graph.agg_ends, graph.agg_ell)
        ),
    )


def bin_key(graph: CompiledFactorGraph,
            params: Dict[str, Any]) -> Tuple:
    """The scheduler's bin key: structure signature + solver params."""
    return (
        structure_signature(graph),
        tuple((k, params[k]) for k in PARAM_KEYS),
    )


def affinity_key(dcop, params: Optional[Dict[str, Any]] = None) -> str:
    """Router-side structure-affinity key (serving/router.py): a
    process-stable digest computed from the PROBLEM MODEL, without
    building cost tables.

    The fleet router must group traffic exactly the way a worker's
    :func:`bin_key` will — same-structure requests must land on the
    replica whose compiled program is already warm — but it must not
    pay a full ``compile_dcop`` (hypercube cost-table fill) per
    routed request.  On the serving compile path (``pad_to=1``,
    scatter aggregation) the bin key's structure half is a pure
    function of (variable count, max domain size, per-arity scope
    indices) — precisely what this digest hashes, in the same
    variable order ``compile_dcop`` uses — so two DCOPs share an
    affinity key iff they share a serving bin key
    (partition-equivalence asserted in tests/unit/
    test_fleet_battery.py).  The params half rides along exactly like
    :func:`bin_key`'s; ``prune="auto"`` is keyed as the literal
    string (workers resolve it per structure AFTER compile — the
    router cannot, so auto-pruned traffic may split across at most
    two replicas per structure).
    """
    merged = normalize_params(params)
    var_index = {name: i for i, name in enumerate(dcop.variables)}
    dmax = max((len(v.domain) for v in dcop.variables.values()),
               default=1)
    by_arity: Dict[int, list] = {}
    for c in dcop.constraints.values():
        if c.arity == 0:
            continue
        by_arity.setdefault(c.arity, []).append(
            tuple(var_index[v.name] for v in c.dimensions))
    structure = (
        len(var_index), dmax,
        tuple((arity, tuple(by_arity[arity]))
              for arity in sorted(by_arity)),
        tuple((k, merged[k]) for k in PARAM_KEYS),
    )
    return hashlib.sha1(repr(structure).encode()).hexdigest()


def bin_label(key: Tuple) -> str:
    """Short low-cardinality label for a bin key (metrics/trace): the
    variable-count/domain part of the shape plus a process-stable
    digest of the rest — full keys embed scope bytes and would
    explode label cardinality, and the built-in ``hash`` is
    per-process randomized (labels must survive restarts so merged
    traces from two serving processes correlate by bin)."""
    (var_shape, _buckets, _agg), _params = key
    digest = hashlib.sha1(repr(key).encode()).hexdigest()[:6]
    return f"v{var_shape[0] - 1}d{var_shape[1]}h{digest}"


# --------------------------------------------------------------------- #
# Envelope tier: shape-envelope keys, padding accounting and the
# per-flush pack-vs-solo cost model (ISSUE 11).

class EnvelopeLadder(NamedTuple):
    """Rounding rungs per shape dimension.  Each dimension rounds UP
    to its first rung >= the real size (past the top rung: the next
    power of two — an oversized problem still envelopes, it just gets
    a rarer key).  Powers-of-two-ish defaults keep the compiled
    envelope-program count logarithmic in the traffic's shape
    spread."""

    vars: Tuple[int, ...] = (8, 16, 32, 64, 128, 256, 512, 1024,
                             2048, 4096)
    domain: Tuple[int, ...] = (2, 4, 8, 16, 32, 64, 128)
    rows: Tuple[int, ...] = (8, 16, 32, 64, 128, 256, 512, 1024,
                             2048, 4096, 8192)


DEFAULT_LADDER = EnvelopeLadder()

# Rounding for lane-packed UNIONS (engine/batch.run_lane_packed):
# deliberately MUCH coarser than the grouping ladder.  A union's
# shapes depend on the flush's group composition, and every distinct
# shape is a fresh XLA compile of the whole solve loop (~0.3-0.8 s on
# CPU) — with fine rungs a diverse stream produces a new program
# almost every flush and the compile stalls eat the packing win.
# Power-of-two rungs starting at 64 keep it to a handful of programs
# for small-problem traffic while letting SMALL groups (2-3 members)
# pack into small unions — the pack decision charges the whole padded
# union's cells, so a coarse-only ladder would price pairs out of
# packing entirely.
UNION_LADDER = EnvelopeLadder(
    vars=(64, 128, 256, 512, 1024, 2048, 4096, 16384),
    domain=(2, 4, 8, 16, 32, 64, 128),
    rows=(64, 128, 256, 512, 1024, 2048, 4096, 16384),
)


def ladder_round(n: int, rungs: Sequence[int]) -> int:
    """First rung >= n; past the top, the next power of two >= n."""
    n = max(int(n), 1)
    for r in rungs:
        if r >= n:
            return r
    p = 1
    while p < n:
        p <<= 1
    return p


class Envelope(NamedTuple):
    """One shape envelope: every dimension is an upper bound a graph
    is mask-padded to (engine/batch.pad_graph_to_envelope).  ``rows``
    is arity-sorted ``((arity, padded_rows), ...)`` — the arity SET is
    exact (padding hypercube rank would multiply, not add, waste), the
    row counts are ladder rungs."""

    v_env: int                          # real-variable rows (no sentinel)
    d_env: int                          # padded domain
    rows: Tuple[Tuple[int, int], ...]   # ((arity, rows_env), ...)


def envelope_key(graph: CompiledFactorGraph,
                 ladder: EnvelopeLadder = DEFAULT_LADDER) -> Envelope:
    """The coarse second-tier key above :func:`structure_signature`:
    ladder-rounded shape dimensions only.  Monotone (a graph that
    grows in any dimension never gets a smaller envelope) and covering
    (every dimension >= the graph's real size) — both battery-asserted
    (tests/unit/test_envelope_battery.py)."""
    return Envelope(
        v_env=ladder_round(graph.n_vars, ladder.vars),
        d_env=ladder_round(graph.dmax, ladder.domain),
        rows=tuple(sorted(
            (b.arity, ladder_round(b.n_factors, ladder.rows))
            for b in graph.buckets
        )),
    )


def graph_cells(graph: CompiledFactorGraph) -> int:
    """Device-array elements the MaxSum superstep touches for this
    graph — the work unit of the pack-vs-solo cost model and of the
    honest ``envelope_waste`` accounting (variable table incl.
    sentinel row + every bucket hypercube)."""
    return int(
        graph.var_costs.shape[0] * graph.var_costs.shape[1]
        + sum(b.costs.size for b in graph.buckets)
    )


def envelope_cells(env: Envelope) -> int:
    """:func:`graph_cells` of any graph padded to ``env``."""
    return int(
        (env.v_env + 1) * env.d_env
        + sum(r * env.d_env ** a for a, r in env.rows)
    )


def lane_cells(graph: CompiledFactorGraph, d_env: int) -> int:
    """:func:`graph_cells` of the graph with only its DOMAIN padded to
    ``d_env`` — the per-member work in a lane-packed union dispatch
    (ops/maxsum_lane packing concatenates factors/variables instead of
    padding them, so the only mask waste left is the domain rung)."""
    return int(
        graph.var_costs.shape[0] * d_env
        + sum(b.n_factors * d_env ** b.arity for b in graph.buckets)
    )


def envelope_label(env: Envelope) -> str:
    """Low-cardinality metrics/trace label for an envelope."""
    rows = "_".join(f"a{a}x{r}" for a, r in env.rows)
    return f"env_v{env.v_env}d{env.d_env}_{rows or 'nofactors'}"


# Cost-model constants, fitted on the CPU backend (the affine
# per-dispatch model ``overhead + cycles * (per_cycle + cells *
# per_cell)``; measured points: a 370-cell solo ring at 60 cycles
# costs ~1.1 ms end-to-end, a 3075-cell padded union ~4.9 ms — the
# per-CYCLE fixed op overhead, not the cell work, dominates tiny
# problems, which is why a naive cells-only model over-packs).
# ``PACK_OVERHEAD_MS`` is the per-dispatch fixed cost (jit-cache
# lookup + host launch + result fetch; SolveService exposes it as
# ``envelope_overhead_ms``).  On TPU the fixed costs are larger and
# the cell work cheaper, so this calibration UNDER-estimates the
# packing win there — conservative in the safe direction.
PACK_OVERHEAD_MS = 0.3
MODEL_US_PER_CYCLE = 5.0
MODEL_NS_PER_CELL_CYCLE = 25.0


def modeled_solve_ms(cells: int, max_cycles: int,
                     constants: Optional[Dict[str, Any]] = None
                     ) -> float:
    """Affine dispatch-compute model (ms), excluding the per-dispatch
    fixed overhead.

    ``constants`` overrides the compiled-in CPU-fitted defaults with
    online-fitted ones (engine/autotune.fitted_pack_constants — keys
    ``us_per_cycle`` / ``ns_per_cell_cycle``): the self-tuning pack
    planner feeds measured ledgers of past dispatches back into the
    very model that prices the next one."""
    us_per_cycle = MODEL_US_PER_CYCLE
    ns_per_cell = MODEL_NS_PER_CELL_CYCLE
    if constants:
        us_per_cycle = float(
            constants.get("us_per_cycle", us_per_cycle))
        ns_per_cell = float(
            constants.get("ns_per_cell_cycle", ns_per_cell))
    return max_cycles * (us_per_cycle * 1e-3
                         + cells * ns_per_cell * 1e-6)


def solve_prior_ms(real_cells: int, max_cycles: int,
                   portfolio_ms: Optional[float] = None,
                   race_cycles: int = 60,
                   constants: Optional[Dict[str, Any]] = None
                   ) -> Tuple[float, str]:
    """Per-structure solo solve-time prior (ms) for the cost model.

    When the PR-10 portfolio racer has a cached time-to-cost entry for
    the structure (engine/autotune.cached_portfolio_timing_ms — a real
    measured solve of ``race_cycles`` cycles on this backend), that is
    the prior, scaled to the request's cycle budget.  Otherwise the
    affine model — honest about being a model (source ``"model"``), so
    the decision record shows which dispatches were decided on
    measurement and which on estimate."""
    if portfolio_ms is not None and portfolio_ms > 0:
        return (portfolio_ms * max_cycles / max(race_cycles, 1),
                "portfolio")
    return (modeled_solve_ms(real_cells, max_cycles,
                             constants=constants), "model")


def lane_union_cells(graphs: Sequence[CompiledFactorGraph],
                     d_env: int,
                     ladder: EnvelopeLadder = UNION_LADDER) -> int:
    """Cells of the PADDED lane union these members would produce
    (mirrors engine/batch.run_lane_packed's rounding) — what the pack
    decision must charge, since the union's sentinel-row padding costs
    real per-cycle time whether or not any member needed it."""
    v_total = sum(g.n_vars for g in graphs)
    rows: Dict[int, int] = {}
    for g in graphs:
        for b in g.buckets:
            rows[b.arity] = rows.get(b.arity, 0) + b.n_factors
    v_env = ladder_round(v_total, ladder.vars)
    return int(
        (v_env + 1) * d_env
        + sum(ladder_round(r, ladder.rows) * d_env ** a
              for a, r in rows.items())
    )


def pack_decision(real_cells: Sequence[int],
                  prior_ms: Sequence[float],
                  packed_cells_total: int,
                  max_cycles: int,
                  overhead_ms: float = PACK_OVERHEAD_MS,
                  constants: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """The per-flush envelope decision: does ONE padded dispatch beat
    N solo dispatches for this group?

    Solo side: each member's measured-or-modeled solve prior plus one
    dispatch overhead each.  Packed side: one overhead plus the affine
    model over the WHOLE padded dispatch's cells
    (``packed_cells_total`` — envelope lanes or the rounded lane
    union, padding included: masked cells still burn per-cycle time).
    Work is summed, not maxed — honest for the CPU backend where
    batched lanes serialize, conservative for TPU where they share
    vector units (a pack that wins under the sum model wins harder on
    chip).  Returns the full modeled record so scheduler decisions
    are replayable in tests and auditable in /stats.

    ``constants`` threads online-fitted model constants through
    (see :func:`modeled_solve_ms`); the decision records where its
    constants came from (``constants_source: fitted|default``) so an
    operator reading ``envelope_decisions`` can tell a measured
    verdict from a cold-start one."""
    n = len(real_cells)
    if constants and "overhead_ms" in constants \
            and float(constants["overhead_ms"]) > 0:
        overhead_ms = float(constants["overhead_ms"])
    solo_ms = sum(prior_ms) + overhead_ms * n
    packed_ms = overhead_ms + modeled_solve_ms(
        packed_cells_total, max_cycles, constants=constants)
    real_total = sum(real_cells)
    return {
        "n": n,
        "packed": bool(n > 1 and packed_ms < solo_ms),
        "solo_ms": round(solo_ms, 4),
        "packed_ms": round(packed_ms, 4),
        "overhead_ms": round(overhead_ms, 4),
        "packed_cells": int(packed_cells_total),
        "waste": round(
            1.0 - real_total / max(packed_cells_total, 1), 4),
        "constants_source": "fitted" if constants else "default",
    }

"""Trace-demo gate: solve a small graph-coloring instance with
``--trace`` + ``--metrics`` through the real CLI and assert the
artifacts validate — the Chrome trace loads as JSON with well-nested
spans and the expected span kinds, the metrics JSONL parses with a
monotone cycle counter, the Prometheus dump is well-formed, and
``pydcop trace summary`` aggregates the file without error.

Run: ``make trace-demo`` (part of ``make test``).  Exit 0 = clean.
"""

import json
import os
import re
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

DCOP_YAML = """\
name: trace_demo
objective: min
domains:
  colors:
    values: [R, G, B]
variables:
  v0: {domain: colors}
  v1: {domain: colors}
  v2: {domain: colors}
  v3: {domain: colors}
constraints:
  c0:
    type: intention
    function: 10 if v0 == v1 else 0
  c1:
    type: intention
    function: 10 if v1 == v2 else 0
  c2:
    type: intention
    function: 10 if v2 == v3 else 0
  c3:
    type: intention
    function: 10 if v3 == v0 else 0
agents: [a0, a1, a2, a3]
"""

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (-?[0-9.e+-]+|\+Inf)$"
)


def fail(message: str) -> int:
    print(f"trace_demo: FAIL: {message}")
    return 1


def main() -> int:
    from pydcop_tpu.dcop_cli import main as cli_main
    from pydcop_tpu.observability.trace import (
        check_well_nested,
        load_trace_file,
    )

    with tempfile.TemporaryDirectory(prefix="trace_demo_") as tmp:
        dcop_file = os.path.join(tmp, "coloring.yaml")
        with open(dcop_file, "w", encoding="utf-8") as f:
            f.write(DCOP_YAML)
        trace_file = os.path.join(tmp, "trace.json")
        metrics_file = os.path.join(tmp, "metrics.jsonl")
        out_file = os.path.join(tmp, "result.json")

        rc = cli_main([
            "--output", out_file,
            "solve", "-a", "maxsum", "-c", "60",
            "--trace", trace_file, "--metrics", metrics_file,
            "--metrics_every", "10", dcop_file,
        ])
        if rc != 0:
            return fail(f"pydcop solve exited {rc}")
        result = json.load(open(out_file, encoding="utf-8"))
        if result.get("violation") != 0:
            return fail(f"demo solve left violations: {result}")

        # 1. Chrome trace: json loads, spans well-nested, the engine
        # span kinds present.
        events = load_trace_file(trace_file)
        if not events:
            return fail("trace file has no events")
        try:
            check_well_nested(events)
        except ValueError as e:
            return fail(f"trace spans not well nested: {e}")
        names = {ev.get("name") for ev in events}
        missing = {"solve", "engine_segment", "chunk"} - names
        if missing:
            return fail(f"trace missing span kinds: {sorted(missing)}")

        # 2. Metrics JSONL: parses, monotone cycle counter.
        rows = [json.loads(line)
                for line in open(metrics_file, encoding="utf-8")]
        if not rows:
            return fail("metrics file has no snapshots")
        cycles = [row["cycle"] for row in rows]
        if cycles != sorted(cycles) or cycles[-1] <= 0:
            return fail(f"cycle counter not monotone: {cycles}")

        # 3. Prometheus dump: HELP/TYPE lines + parsable samples.
        prom = open(f"{metrics_file}.prom", encoding="utf-8").read()
        if "# HELP pydcop_cycles_total" not in prom or \
                "# TYPE pydcop_cycles_total counter" not in prom:
            return fail("prometheus dump missing cycle counter family")
        for line in prom.strip().splitlines():
            if not line.startswith("#") and not _PROM_SAMPLE.match(line):
                return fail(f"unparsable prometheus sample: {line!r}")

        # 4. The summary command aggregates the trace without error.
        rc = cli_main(["trace", "summary", trace_file])
        if rc != 0:
            return fail(f"pydcop trace summary exited {rc}")

    print("trace_demo: OK (trace + metrics + summary all validate)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""API tests: full in-process stack through solve(backend='thread').

Mirrors the reference's api tests (tests/api/test_api_solve.py:36-44):
real orchestrator + threaded agents + in-process transport, bounded by
short timeouts, asserting on solution quality.
"""

import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

from fixtures_paths import local

FIXTURE = local("coloring_chain.yaml")


def _dcop():
    return load_dcop_from_file(FIXTURE)


def test_thread_solve_maxsum():
    res = solve(_dcop(), "maxsum", backend="thread", timeout=3)
    assert res["violations"] == 0
    assert res["cost"] == pytest.approx(-0.6)
    assert set(res["assignment"]) == {"w1", "w2", "w3", "w4"}
    assert res["msg_count"] > 0


@pytest.mark.parametrize("algo", ["dsa", "mgm"])
def test_thread_solve_local_search(algo):
    res = solve(_dcop(), algo, backend="thread", timeout=3)
    assert res["violations"] == 0
    # Stochastic local search over the clash constraints: any proper
    # coloring of the chain is a legitimate terminal state (unary
    # preferences only break ties), costs span [-0.6, 0.6].
    a = res["assignment"]
    for left, right in [("w1", "w2"), ("w2", "w3"), ("w3", "w4")]:
        assert a[left] != a[right]
    assert -0.6 - 1e-6 <= res["cost"] <= 0.6 + 1e-6
    assert res["msg_count"] > 0


def test_thread_solve_with_stop_cycle():
    res = solve(
        _dcop(), "dsa", backend="thread", timeout=10,
        algo_params={"stop_cycle": 30},
    )
    assert res["status"] == "FINISHED"
    assert res["cycles"] == 30


def test_thread_solve_ncbb():
    # NCBB agent mode runs the INIT phase (greedy top-down + bound
    # propagation) and terminates cleanly; the assignment is the greedy
    # one, so only feasibility-level quality is guaranteed.
    res = solve(_dcop(), "ncbb", backend="thread", timeout=5)
    assert res["status"] == "FINISHED"
    assert set(res["assignment"]) == {"w1", "w2", "w3", "w4"}


def test_thread_and_device_agree():
    d = _dcop()
    r_thread = solve(d, "maxsum", backend="thread", timeout=3)
    r_device = solve(d, "maxsum", backend="device", max_cycles=100)
    assert r_thread["cost"] == pytest.approx(r_device["cost"])

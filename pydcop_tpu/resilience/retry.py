"""Retry policies: exponential backoff + jitter + deadline + breaker.

One policy object serves every unreliable edge in the system — the
HTTP transport's delivery loop, ``Messaging._send_remote``, and the
multihost coordinator join — so operational tuning is a handful of
environment variables instead of per-call-site constants (the
reference hard-codes its retry constants inline,
pydcop/infrastructure/communication.py:66-78).

Determinism: jitter draws from a caller-supplied ``random.Random`` so
chaos tests can fix the whole retry trajectory with one seed; without
one the delays are deterministic (pure exponential, no jitter).
"""

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

logger = logging.getLogger("pydcop.resilience.retry")


class RetryExhaustedError(Exception):
    """All attempts failed; ``last_error`` holds the final cause."""

    def __init__(self, message: str, last_error: Optional[BaseException]):
        super().__init__(message)
        self.last_error = last_error


class CircuitOpenError(Exception):
    """The circuit breaker is open: the call was not attempted."""


class CircuitBreaker:
    """Per-destination failure latch (closed → open → half-open).

    After ``failure_threshold`` consecutive failures the circuit opens
    and :meth:`allow` answers False — callers skip the doomed attempt
    (and its connect timeout) entirely.  After ``reset_timeout``
    seconds one probe call is allowed through (half-open); its outcome
    closes or re-opens the circuit.  Thread-safe: transports share one
    breaker per destination across sender threads.
    """

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout: float = 2.0,
                 name: Optional[str] = None):
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.name = name  # destination label for trace/metrics events
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if time.monotonic() - self._opened_at >= self.reset_timeout:
                return "half_open"
            return "open"

    def allow(self) -> bool:
        """True when a call may be attempted now.  In the half-open
        state only ONE caller gets the probe; others stay blocked until
        its outcome is recorded."""
        with self._lock:
            if self._opened_at is None:
                return True
            if time.monotonic() - self._opened_at < self.reset_timeout:
                return False
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self):
        with self._lock:
            reclosed = self._opened_at is not None
            self._failures = 0
            self._opened_at = None
            self._probing = False
        if reclosed:
            from pydcop_tpu.observability.trace import tracer

            if tracer.enabled:
                tracer.instant("breaker_close", "resilience",
                               dest=self.name or "?")

    def record_failure(self):
        with self._lock:
            self._failures += 1
            self._probing = False
            tripped = False
            if self._failures >= self.failure_threshold:
                if self._opened_at is None:
                    tripped = True
                    logger.debug(
                        "Circuit opened after %d failures", self._failures
                    )
                # A failed half-open probe re-arms the full timeout.
                self._opened_at = time.monotonic()
        if tripped:
            # A trip is a rare, operationally-significant event: it is
            # counted unconditionally (breaker state belongs in every
            # metrics dump) and traced when a trace is being taken.
            from pydcop_tpu.observability.metrics import registry
            from pydcop_tpu.observability.trace import tracer

            registry.counter(
                "pydcop_breaker_trips_total",
                "Circuit breakers opened after repeated failures",
            ).inc(dest=self.name or "?")
            if tracer.enabled:
                tracer.instant("breaker_trip", "resilience",
                               dest=self.name or "?",
                               failures=self._failures)

    def reset(self):
        self.record_success()


def _env_float(name: str, default: Optional[float]) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return float(raw)


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with optional jitter and overall deadline.

    ``delay_for(attempt)`` (attempt 1 = the delay before the first
    RE-try) is ``min(base_delay * multiplier**(attempt-1), max_delay)``
    plus up to ``jitter`` fraction of itself, drawn from ``rng`` when
    one is given.  ``deadline`` bounds the whole :meth:`call` (first
    attempt included); ``max_attempts`` bounds the attempt count.
    Either bound alone is enough; with neither the policy would retry
    forever, so ``call`` requires at least one.
    """

    max_attempts: Optional[int] = 5
    base_delay: float = 0.1
    max_delay: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline: Optional[float] = None

    @classmethod
    def from_env(cls, prefix: str = "PYDCOP_RETRY_",
                 **defaults) -> "RetryPolicy":
        """Build a policy from ``<prefix>MAX_ATTEMPTS / BASE_DELAY /
        MAX_DELAY / MULTIPLIER / JITTER / DEADLINE`` env vars, falling
        back to ``defaults`` then the dataclass defaults."""
        base = cls(**defaults)
        raw_attempts = os.environ.get(prefix + "MAX_ATTEMPTS")
        max_attempts = (
            int(raw_attempts) if raw_attempts not in (None, "")
            else base.max_attempts
        )
        return cls(
            max_attempts=max_attempts,
            base_delay=_env_float(prefix + "BASE_DELAY", base.base_delay),
            max_delay=_env_float(prefix + "MAX_DELAY", base.max_delay),
            multiplier=_env_float(prefix + "MULTIPLIER", base.multiplier),
            jitter=_env_float(prefix + "JITTER", base.jitter),
            deadline=_env_float(prefix + "DEADLINE", base.deadline),
        )

    def delay_for(self, attempt: int, rng=None) -> float:
        """Backoff before retry ``attempt`` (1-based), jittered."""
        delay = min(
            self.base_delay * self.multiplier ** max(attempt - 1, 0),
            self.max_delay,
        )
        if self.jitter and rng is not None:
            delay += delay * self.jitter * rng.random()
        return delay

    def call(self, fn: Callable, *args,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             rng=None,
             sleep: Callable[[float], None] = time.sleep,
             breaker: Optional[CircuitBreaker] = None,
             on_retry: Optional[Callable] = None,
             **kwargs):
        """Run ``fn`` under this policy; returns its result.

        Raises :class:`CircuitOpenError` without attempting when
        ``breaker`` is open, and :class:`RetryExhaustedError` once
        attempts or the deadline run out.  ``on_retry(attempt, error,
        delay)`` is called before each backoff sleep.
        """
        if self.max_attempts is None and self.deadline is None:
            raise ValueError(
                "RetryPolicy.call needs max_attempts or deadline"
            )
        start = time.monotonic()
        attempt = 0
        last_error: Optional[BaseException] = None
        while True:
            if breaker is not None and not breaker.allow():
                raise CircuitOpenError(
                    f"circuit open (state={breaker.state})"
                )
            attempt += 1
            try:
                result = fn(*args, **kwargs)
            except retry_on as e:
                last_error = e
                if breaker is not None:
                    breaker.record_failure()
            else:
                if breaker is not None:
                    breaker.record_success()
                return result
            if self.max_attempts is not None and \
                    attempt >= self.max_attempts:
                raise RetryExhaustedError(
                    f"{attempt} attempts failed: {last_error}",
                    last_error,
                )
            delay = self.delay_for(attempt, rng)
            if self.deadline is not None and \
                    time.monotonic() + delay - start > self.deadline:
                raise RetryExhaustedError(
                    f"deadline {self.deadline}s exceeded after "
                    f"{attempt} attempts: {last_error}",
                    last_error,
                )
            if on_retry is not None:
                try:
                    on_retry(attempt, last_error, delay)
                except Exception:
                    logger.exception("on_retry callback failed")
            from pydcop_tpu.observability.trace import tracer

            if tracer.enabled:
                tracer.instant(
                    "retry", "resilience", attempt=attempt,
                    delay=delay, error=str(last_error)[:200],
                )
            sleep(delay)

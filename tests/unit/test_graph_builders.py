"""Computation-graph builder invariant tests: pseudotree DFS
properties and ordered-graph total order (reference
computations_graph/pseudotree.py:325-470, ordered_graph.py:119-182 —
previously exercised only indirectly through dpop/syncbb solves)."""

import numpy as np

from pydcop_tpu.computations_graph import ordered_graph, pseudotree
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str

D = Domain("d", "", [0, 1, 2])


def _problem(n=8, seed=0, extra_edges=4):
    rng = np.random.default_rng(seed)
    vs = [Variable(f"v{i}", D) for i in range(n)]
    cs = [
        constraint_from_str(
            f"c{i}", f"v{i} + v{i + 1}", [vs[i], vs[i + 1]])
        for i in range(n - 1)
    ]
    k = 0
    seen = {(i, i + 1) for i in range(n - 1)}
    while k < extra_edges:
        i, j = sorted(rng.choice(n, size=2, replace=False))
        if (i, j) in seen:
            continue
        seen.add((i, j))
        cs.append(constraint_from_str(
            f"x{k}", f"v{i} * v{j}", [vs[i], vs[j]]))
        k += 1
    return vs, cs


class TestPseudoTree:
    def _tree(self, **kw):
        vs, cs = _problem(**kw)
        return (
            pseudotree.build_computation_graph(
                variables=vs, constraints=cs),
            vs, cs,
        )

    def test_single_root_and_parent_links(self):
        tree, vs, _ = self._tree()
        roots = tree.roots
        assert len(roots) == 1
        for node in tree.nodes:
            if node.is_root:
                assert node.parent is None
            else:
                assert node.parent is not None
                parent = tree.computation(node.parent)
                assert node.name in parent.children

    def test_every_variable_is_a_node(self):
        tree, vs, _ = self._tree()
        assert sorted(n.name for n in tree.nodes) == sorted(
            v.name for v in vs)

    def test_dfs_property_constraints_on_ancestor_path(self):
        """Pseudo-tree invariant: every constraint's variables lie on
        one root-to-leaf path (neighbors are ancestors/descendants,
        never in different branches)."""
        tree, vs, cs = self._tree(seed=3, extra_edges=6)

        def ancestors(name):
            out = set()
            node = tree.computation(name)
            while node.parent is not None:
                out.add(node.parent)
                node = tree.computation(node.parent)
            return out

        for c in cs:
            names = [v.name for v in c.dimensions]
            for a in names:
                for b in names:
                    if a == b:
                        continue
                    assert (
                        b in ancestors(a) or a in ancestors(b)
                    ), f"{a} and {b} ({c.name}) are in different branches"

    def test_pseudo_parent_links_symmetry(self):
        tree, _, _ = self._tree(seed=5, extra_edges=6)
        for node in tree.nodes:
            for pp in node.pseudo_parents:
                assert node.name in tree.computation(pp).pseudo_children

    def test_depths_consistent(self):
        tree, _, _ = self._tree()
        depths = pseudotree.node_depths(tree)
        for node in tree.nodes:
            if node.is_root:
                assert depths[node.name] == 0
            else:
                assert depths[node.name] == depths[node.parent] + 1


class TestOrderedGraph:
    def test_lexical_total_order(self):
        vs, cs = _problem(n=5)
        og = ordered_graph.build_computation_graph(
            variables=vs, constraints=cs)
        nodes = {n.name: n for n in og.nodes}
        # Lexical order: v0 first (no previous), v4 last (no next).
        chain = []
        current = next(
            n for n in og.nodes if n.previous_node is None)
        while current is not None:
            chain.append(current.name)
            current = (
                nodes[current.next_node]
                if current.next_node else None
            )
        assert chain == sorted(v.name for v in vs)

    def test_constraints_attached_to_nodes(self):
        vs, cs = _problem(n=5)
        og = ordered_graph.build_computation_graph(
            variables=vs, constraints=cs)
        attached = set()
        for node in og.nodes:
            for c in node.constraints:
                attached.add(c.name)
        assert attached == {c.name for c in cs}
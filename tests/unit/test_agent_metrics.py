"""Agent runtime metrics tests: t_active activity accounting,
per-computation cycle counts, external-message counters and the
messaging priority queue ordering (reference agents.py:806-812
activity time, AgentMetrics :878; communication.py priorities
:495-497)."""

import time

from pydcop_tpu.infrastructure.agents import Agent
from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    MSG_MGT,
    MSG_VALUE,
    ComputationMessage,
    InProcessCommunicationLayer,
    Messaging,
)
from pydcop_tpu.infrastructure.computations import (
    Message,
    MessagePassingComputation,
    register,
)


class Busy(MessagePassingComputation):
    """Computation that burns measurable time per message."""

    def __init__(self, name="busy", delay=0.02):
        super().__init__(name)
        self.delay = delay
        self.handled = 0

    @register("work")
    def _on_work(self, sender, msg, t):
        time.sleep(self.delay)
        self.handled += 1


def test_t_active_accumulates_and_ratio_reported():
    comm = InProcessCommunicationLayer()
    agent = Agent("a1", comm)
    comp = Busy()
    agent.add_computation(comp)
    agent.start()
    try:
        agent.run()
        for _ in range(5):
            agent.messaging.post_msg(
                "ext", "busy", Message("work", None), MSG_ALGO)
        deadline = time.monotonic() + 5
        while comp.handled < 5 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert comp.handled == 5
        # 5 messages x >=20ms of handling were accounted.
        assert agent.t_active >= 5 * 0.02 * 0.8
        metrics = agent.metrics()
        assert 0 < metrics["activity_ratio"] <= 1
    finally:
        agent.clean_shutdown(2)


def test_messaging_priority_ordering():
    """Queue pops follow priority classes, not arrival order:
    MGT(10) < VALUE(15) < ALGO(20)."""
    comm = InProcessCommunicationLayer()
    messaging = Messaging("a1", comm)
    messaging.register_computation("c")
    messaging.post_msg("x", "c", Message("algo", 1), MSG_ALGO)
    messaging.post_msg("x", "c", Message("value", 2), MSG_VALUE)
    messaging.post_msg("x", "c", Message("mgt", 3), MSG_MGT)
    kinds = [messaging.next_msg(0.1).msg.type for _ in range(3)]
    assert kinds == ["mgt", "value", "algo"]


def test_messaging_fifo_within_priority():
    comm = InProcessCommunicationLayer()
    messaging = Messaging("a1", comm)
    messaging.register_computation("c")
    for i in range(4):
        messaging.post_msg("x", "c", Message("algo", i), MSG_ALGO)
    contents = [messaging.next_msg(0.1).msg.content for _ in range(4)]
    assert contents == [0, 1, 2, 3]


def test_external_message_counters():
    """Messages leaving the agent are counted/sized per source
    computation (reference communication.py:542-577)."""
    comm_a = InProcessCommunicationLayer()
    messaging_a = Messaging("a", comm_a)

    comm_b = InProcessCommunicationLayer()
    messaging_b = Messaging("b", comm_b)
    messaging_b.register_computation("remote")

    class Disco:
        def agent_address(self, name):
            return comm_b

        def computation_agent(self, comp):
            return {"remote": "b"}.get(comp, "a")

    comm_a.discovery = Disco()

    messaging_a.post_msg(
        "local", "remote", Message("algo", "xyz"), MSG_ALGO)
    assert messaging_a.count_ext_msg.get("local") == 1
    assert messaging_a.size_ext_msg.get("local", 0) > 0
    # And it arrived on b's queue.
    got = messaging_b.next_msg(0.5)
    assert got is not None and got.msg.content == "xyz"


def test_agent_metrics_cycle_counts():
    from pydcop_tpu.algorithms import AlgorithmDef, ComputationDef
    from pydcop_tpu.computations_graph import constraints_hypergraph as chg
    from pydcop_tpu.dcop.objects import Domain, Variable
    from pydcop_tpu.dcop.relations import constraint_from_str
    from pydcop_tpu.infrastructure.agent_algorithms import DsaComputation

    d = Domain("d", "", [0, 1])
    v0, v1 = Variable("v0", d), Variable("v1", d)
    c = constraint_from_str("c", "v0 + v1", [v0, v1])
    cg = chg.build_computation_graph(
        variables=[v0, v1], constraints=[c])
    algo = AlgorithmDef.build_with_default_param("dsa", mode="min")
    comp = DsaComputation(
        ComputationDef(cg.computation("v0"), algo))

    comm = InProcessCommunicationLayer()
    agent = Agent("a1", comm)
    agent.add_computation(comp)
    metrics = agent.metrics()
    assert metrics["cycles"]["v0"] == 0
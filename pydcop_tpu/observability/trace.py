"""Process-wide tracer: timestamped spans with parent/child
correlation, exported as Chrome ``trace_event`` JSON or JSONL.

The runtime is threaded (one thread per agent, HTTP server threads,
retry sweepers, fault timers); a single locked event list would
serialize every instrumented site on one mutex.  Instead each thread
appends to its own buffer (``threading.local``) — the only lock is
taken once per thread per session, when the buffer is registered for
export — so recording is a list append plus a dict build.

Disabled (the default) costs ONE attribute check: every instrumented
site guards on ``tracer.enabled``, :meth:`Tracer.span` returns a
shared no-op context manager singleton (no allocation), and
:meth:`Tracer.instant` returns before touching its arguments.  The
zero-overhead contract is asserted in the observability battery.

Span events carry ``id``/``parent`` correlation ids (a per-thread span
stack): a message-handling span opened inside an agent-step span
records the step as its parent, so one trace file reconstructs the
whole causal tree of a chaos run.  Chrome ``trace_event`` output loads
directly in ``chrome://tracing`` / Perfetto (spans are ``ph:"X"``
complete events, instants ``ph:"i"``); JSONL output is one event per
line for ad-hoc ``jq``/pandas processing.

Multi-process runs: every exported file carries a HEADER with the
process/host identity and a monotonic-to-wall clock anchor
(``perf_counter`` timestamps are only comparable within one process).
:func:`merge_traces` uses the anchors to align N per-process traces
onto one wall-clock axis and namespaces their thread lanes, so a
distributed run collapses into a single well-nested Perfetto tab;
:func:`diff_trace_summaries` compares two traces span-name by
span-name (count/total/p50 deltas, regression flags) — the ``pydcop
trace merge`` / ``trace diff`` commands drive both.

Request-scoped causality (the serve plane): :meth:`Tracer.context`
binds args (e.g. a request ``trace_id``, or a batch's ``trace_ids``)
onto the CURRENT THREAD for the duration of a ``with`` block — every
span and instant recorded inside carries them, so engine internals
are tagged with the requests riding a dispatch without the engine
knowing about requests.  :func:`query_request` filters a trace down
to one request's events and rebuilds its span tree (``pydcop trace
query --request ID``).

Flight recorder: :meth:`Tracer.set_flight` attaches an always-on
bounded ring (observability/flight.py) that receives events EVEN
WHILE file tracing is off.  Sites whose events belong in a
postmortem guard on ``tracer.active`` (true when either the session
tracer or the flight ring wants events); per-message hot paths keep
guarding on ``tracer.enabled`` so the ring holds signal, not message
spam.
"""

import itertools
import json
import math
import os
import socket
import threading
import time
from collections import defaultdict
from typing import (
    Any,
    Dict,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
)

_US = 1e6  # trace_event timestamps are microseconds

HEADER_KEY = "pydcop_trace_header"
_HEADER_VERSION = 1


class TraceFileError(ValueError):
    """A trace file that cannot be read as events: missing, empty,
    truncated mid-write, or not Chrome-JSON/JSONL at all.  Commands
    catch this and print the message instead of a traceback."""


def trace_header() -> Dict[str, Any]:
    """Identity + clock anchor stamped into every exported trace.

    ``anchor_perf_us`` and ``anchor_unix_us`` are sampled
    back-to-back: their difference maps this process's
    ``perf_counter`` timeline onto the wall clock, which is what lets
    :func:`merge_traces` align traces from different processes (each
    process's perf_counter has an arbitrary epoch)."""
    return {
        "version": _HEADER_VERSION,
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "anchor_perf_us": time.perf_counter() * _US,
        "anchor_unix_us": time.time() * _US,
    }


class _NoopSpan:
    """Shared do-nothing context manager returned while disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """An open span; records a complete (``ph:"X"``) event on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "span_id",
                 "parent_id", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.span_id = next(tracer._ids)
        self.parent_id = 0
        self._t0 = 0.0

    def __enter__(self):
        stack = self._tracer._stack()
        self.parent_id = stack[-1] if stack else 0
        stack.append(self.span_id)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        stack = self._tracer._stack()
        if stack and stack[-1] == self.span_id:
            stack.pop()
        self._tracer._record({
            "name": self.name,
            "cat": self.cat,
            "ph": "X",
            "ts": self._t0 * _US,
            "dur": (t1 - self._t0) * _US,
            "id": self.span_id,
            "parent": self.parent_id,
            "args": self.args,
        })
        return False


class _TraceContext:
    """Pushes bound args for the current thread; see Tracer.context."""

    __slots__ = ("_tracer", "args")

    def __init__(self, tracer: "Tracer", args: Dict[str, Any]):
        self._tracer = tracer
        self.args = args

    def __enter__(self):
        local = self._tracer._ensure_local()
        local.ctx_stack.append(self.args)
        self._tracer._rebuild_ctx()
        return self

    def __exit__(self, *exc):
        local = self._tracer._ensure_local()
        # Remove by identity, not equality (two contexts may bind
        # equal dicts), and survive an enable() that reset the stack
        # mid-block.
        local.ctx_stack[:] = [
            a for a in local.ctx_stack if a is not self.args
        ]
        self._tracer._rebuild_ctx()
        return False


class Tracer:
    """Per-thread-buffered span/instant recorder.

    Lifecycle: :meth:`enable` clears previous events and starts a
    session; :meth:`disable` stops recording (events stay readable for
    export); :meth:`events` / :meth:`export_chrome` /
    :meth:`export_jsonl` read them back.

    ``active`` is the recording-wanted flag call sites guard on when
    their events should also reach the flight recorder's always-on
    ring: it is true while the session tracer is enabled OR a flight
    ring is attached (:meth:`set_flight`).  ``enabled`` alone still
    gates the per-message hot paths.
    """

    def __init__(self):
        self.enabled = False
        # Attached flight ring (observability/flight.FlightRecorder)
        # or None; ``active`` is kept in sync so hot sites pay one
        # attribute check, not two.
        self.flight = None
        self.active = False
        self._lock = threading.Lock()
        self._local = threading.local()
        # (tid, thread name, buffer) per registered thread.
        self._buffers: List[tuple] = []
        # Bumping the generation invalidates every thread's cached
        # buffer, so enable() drops stale events without touching
        # other threads' locals.
        self._generation = 0
        # Monotone lane ids, independent of _buffers length: flight-
        # only threads get a tid without a registration.
        self._tid_counter = 0
        self._ids = itertools.count(1)

    # -- recording ----------------------------------------------------- #

    def _ensure_local(self):
        if getattr(self._local, "gen", None) != self._generation:
            buf: list = []
            thread = threading.current_thread()
            self._local.buf = buf
            self._local.stack = []
            # Context-binding state survives nothing across a
            # generation bump: a fresh session starts unbound (open
            # _TraceContext blocks re-register on exit harmlessly).
            self._local.ctx_stack = []
            self._local.ctx = {}
            self._local.gen = self._generation
            with self._lock:
                # Synthetic tid, not thread.ident: the OS reuses
                # idents once a thread exits (killed agents, repair
                # threads), which would merge two threads' lanes and
                # break span nesting within one exported lane.
                self._tid_counter += 1
                self._local.tid = self._tid_counter
                # Register the buffer for export ONLY while a file
                # session is recording: in flight-only mode
                # (enabled=False, ring attached — the production
                # serve default) events go to the bounded ring and
                # the buffer stays empty, so keeping a registration
                # per short-lived thread (one HTTP handler thread
                # per request) would grow _buffers without bound.
                # enable() bumps the generation, so a thread first
                # seen in flight-only mode re-registers here the
                # moment a session starts.
                if self.enabled:
                    self._buffers.append(
                        (self._local.tid, thread.name, buf))
        return self._local

    def _buf(self) -> list:
        return self._ensure_local().buf

    def _stack(self) -> list:
        return self._ensure_local().stack

    def _rebuild_ctx(self):
        local = self._ensure_local()
        flat: Dict[str, Any] = {}
        for args in local.ctx_stack:
            flat.update(args)
        local.ctx = flat

    def context(self, **args) -> _TraceContext:
        """Bind args onto every span/instant the CURRENT THREAD
        records inside the ``with`` block (explicit event args win on
        key collision).  The serve dispatch path binds the batch's
        ``trace_ids`` here, so engine spans recorded underneath are
        request-attributable without the engine knowing about
        requests.  Nestable; inner bindings shadow outer ones."""
        return _TraceContext(self, args)

    def _record(self, event: Dict[str, Any]):
        enabled = self.enabled
        flight = self.flight
        if not enabled and flight is None:
            return
        local = self._ensure_local()
        ctx = local.ctx
        if ctx:
            # Merge INTO the existing args dict (explicit event args
            # win) rather than replacing it: timed_jit_call mutates
            # span.args after exit to attach measured XLA cost, and
            # the recorded event must keep holding that same dict by
            # reference or the attribution is silently lost whenever
            # a trace context is bound (the serve dispatch path).
            args = event.get("args")
            if args is None:
                event["args"] = dict(ctx)
            else:
                for k, v in ctx.items():
                    args.setdefault(k, v)
        event["tid"] = local.tid
        if enabled:
            local.buf.append(event)
        if flight is not None:
            flight.record(event)

    def span(self, name: str, cat: str = "default", **args) -> Any:
        """Context manager recording a complete span on exit.

        Hot call sites should still guard on ``tracer.enabled`` (or
        ``tracer.active`` for events that belong in flight-recorder
        postmortems) so the kwargs dict is never built while off."""
        if not self.active:
            return NOOP_SPAN
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "default", **args):
        """Record a point-in-time event."""
        if not self.active:
            return
        parent = self._stack()
        self._record({
            "name": name,
            "cat": cat,
            "ph": "i",
            "ts": time.perf_counter() * _US,
            "id": next(self._ids),
            "parent": parent[-1] if parent else 0,
            "args": args,
        })

    def complete(self, name: str, cat: str = "default", *,
                 t0: float, t1: float, **args):
        """Record an already-finished span from explicit
        ``perf_counter`` timestamps (seconds).  For intervals whose
        start lived on no thread — a request's queue wait starts on
        the submitting thread and ends on the scheduler thread; the
        dispatcher records it retroactively here."""
        if not self.active:
            return
        self._record({
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": float(t0) * _US,
            "dur": max(float(t1) - float(t0), 0.0) * _US,
            "id": next(self._ids),
            "parent": 0,
            "args": args,
        })

    # -- lifecycle ----------------------------------------------------- #

    def set_flight(self, recorder) -> None:
        """Attach (or detach, with ``None``) the always-on flight
        ring.  While attached, ``active`` stays true and every
        recorded event is appended to the ring even when the session
        tracer is disabled."""
        self.flight = recorder
        self.active = self.enabled or recorder is not None

    def enable(self):
        """Start a fresh tracing session (previous events dropped).

        ``_tid_counter`` is NOT reset: the flight ring outlives
        sessions, and re-issuing tid 1.. to the new session's threads
        would merge a pre-session thread's ring events with an
        unrelated post-session thread's lane in a postmortem bundle."""
        with self._lock:
            self._generation += 1
            self._buffers = []
            self.enabled = True
            self.active = True

    def disable(self):
        """Stop recording; buffered events stay readable for export."""
        self.enabled = False
        self.active = self.flight is not None

    def clear(self):
        """Drop all events; recording state unchanged.  Lane ids keep
        counting up (see :meth:`enable`)."""
        with self._lock:
            self._generation += 1
            self._buffers = []

    # -- readback / export --------------------------------------------- #

    def events(self) -> List[Dict[str, Any]]:
        """All recorded events, globally sorted by timestamp."""
        with self._lock:
            buffers = [(tid, name, list(buf))
                       for tid, name, buf in self._buffers]
        merged = [ev for _, _, buf in buffers for ev in buf]
        merged.sort(key=lambda e: e["ts"])
        return merged

    def thread_names(self) -> Dict[int, str]:
        with self._lock:
            return {tid: name for tid, name, _ in self._buffers}

    def export_chrome(self, path: str):
        """Write Chrome ``trace_event`` JSON (open in chrome://tracing
        or https://ui.perfetto.dev)."""
        pid = os.getpid()
        trace_events = [
            {
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid, "args": {"name": name},
            }
            for tid, name in sorted(self.thread_names().items())
        ]
        for ev in self.events():
            out = {
                "name": ev["name"],
                "cat": ev["cat"],
                "ph": ev["ph"],
                "ts": ev["ts"],
                "pid": pid,
                "tid": ev["tid"],
                "args": dict(ev.get("args") or {}),
            }
            if ev["ph"] == "X":
                out["dur"] = ev["dur"]
            else:
                out["s"] = "t"  # thread-scoped instant
            # Correlation ids ride in args: the Chrome schema has no
            # parent field for X events, and viewers ignore extras.
            out["args"]["span_id"] = ev.get("id", 0)
            out["args"]["parent_id"] = ev.get("parent", 0)
            trace_events.append(out)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "traceEvents": trace_events,
                    "displayTimeUnit": "ms",
                    # Viewers ignore unknown top-level keys; trace
                    # merge reads the identity + clock anchor here.
                    HEADER_KEY: trace_header(),
                },
                f, default=str,
            )
        os.replace(tmp, path)

    def export_jsonl(self, path: str):
        """One JSON event per line (jq/pandas-friendly); the first
        line is the process-identity/clock-anchor header."""
        names = self.thread_names()
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps({HEADER_KEY: trace_header()}) + "\n")
            for ev in self.events():
                row = dict(ev)
                row["thread"] = names.get(ev["tid"], str(ev["tid"]))
                f.write(json.dumps(row, default=str) + "\n")
        os.replace(tmp, path)

    def export(self, path: str, fmt: str = "chrome"):
        if fmt == "chrome":
            self.export_chrome(path)
        elif fmt == "jsonl":
            self.export_jsonl(path)
        else:
            raise ValueError(
                f"unknown trace format {fmt!r}: use 'chrome' or 'jsonl'"
            )


tracer = Tracer()


def get_tracer() -> Tracer:
    return tracer


# --------------------------------------------------------------------- #
# trace-file readback + analysis (pydcop trace summary, make trace-demo)


def _parse_trace(path: str) -> Tuple[Optional[Dict[str, Any]],
                                     List[Dict[str, Any]],
                                     Dict[Any, str]]:
    """Internal loader: ``(header, events, thread_names)``.

    ``thread_names`` maps tid -> label, recovered from Chrome
    ``thread_name`` metadata events or per-event ``thread`` fields
    (JSONL) — :func:`merge_traces` labels merged lanes with these.
    """
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as exc:
        raise TraceFileError(f"cannot read trace file {path}: {exc}")
    if not text.strip():
        raise TraceFileError(f"trace file {path} is empty")
    header: Optional[Dict[str, Any]] = None
    try:
        # One JSON document: the Chrome container, a bare list, or a
        # single-line JSONL file (one event object).
        data = json.loads(text)
        if isinstance(data, dict):
            header = data.get(HEADER_KEY)
            if header is not None and not isinstance(header, dict):
                raise TraceFileError(
                    f"trace file {path} has a corrupt header: "
                    f"{HEADER_KEY} is {type(header).__name__}, "
                    "not an object")
            events = data.get("traceEvents")
            if events is None:
                if "ph" not in data:
                    raise TraceFileError(
                        f"{path} parsed as JSON but is not a trace "
                        "(no traceEvents list, not an event object)")
                events = [data]
        else:
            events = data
    except json.JSONDecodeError as exc:
        # Multiple documents: JSONL, one event per line.  A line that
        # does not parse means a truncated/corrupt file — say so.
        events = []
        for n, line in enumerate(text.splitlines(), start=1):
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                if n == 1:
                    if line.lstrip().startswith(
                            '{"' + HEADER_KEY):
                        # The exporter writes the header first, so a
                        # process killed mid-write most often tears
                        # exactly this line — name the failure.
                        raise TraceFileError(
                            f"trace file {path} has a truncated or "
                            "corrupt header line (process died "
                            "mid-export?)")
                    raise TraceFileError(
                        f"{path} is neither Chrome-trace JSON "
                        f"({exc}) nor JSONL (line 1 unparsable)"
                    )
                raise TraceFileError(
                    f"trace file {path} is truncated or corrupt: "
                    f"line {n} is not valid JSON"
                )
            if isinstance(row, dict) and HEADER_KEY in row:
                header = row[HEADER_KEY]
                if not isinstance(header, dict):
                    raise TraceFileError(
                        f"trace file {path} has a corrupt header: "
                        f"{HEADER_KEY} is {type(header).__name__}, "
                        "not an object")
                continue
            events.append(row)
    if not isinstance(events, list):
        raise TraceFileError(
            f"{path} parsed as JSON but holds no event list")
    names: Dict[Any, str] = {}
    kept = []
    for ev in events:
        if not isinstance(ev, dict) or "ph" not in ev:
            continue
        if ev.get("ph") == "M":
            if ev.get("name") == "thread_name":
                label = (ev.get("args") or {}).get("name")
                if label:
                    names[ev.get("tid")] = str(label)
            continue
        if ev.get("thread"):
            names.setdefault(ev.get("tid"), str(ev["thread"]))
        kept.append(ev)
    if events and not kept:
        raise TraceFileError(
            f"{path} parsed as JSON but holds no trace events")
    return header, kept, names


def load_trace(path: str
               ) -> Tuple[Optional[Dict[str, Any]],
                          List[Dict[str, Any]]]:
    """Load ``(header, events)`` from a Chrome-trace JSON or JSONL
    trace file.

    ``header`` is the process-identity/clock-anchor record written by
    the exporters (None for traces from before headers existed).
    Events come back in the normalized internal shape (name/cat/ph/
    ts/dur/tid/args); Chrome metadata events (``ph:"M"``) and the
    header row are dropped from the event list.

    Raises :class:`TraceFileError` — never a bare decode traceback —
    on a missing, empty, truncated or non-trace file.
    """
    header, events, _ = _parse_trace(path)
    return header, events


def load_trace_file(path: str) -> List[Dict[str, Any]]:
    """Events only — see :func:`load_trace` for the (header, events)
    form and the error contract."""
    return load_trace(path)[1]


def _clock_anchor_offset(header: Optional[Dict[str, Any]],
                         path: str) -> Optional[float]:
    """The file's perf_counter→wall-clock rebase offset (µs), or
    None for a legacy headerless/anchorless trace (degraded-merge
    mode).  A header that CARRIES anchor fields but cannot yield a
    finite offset — one field missing, a non-numeric value, NaN/Inf —
    is corrupt, not legacy: raise a :class:`TraceFileError` naming
    the file instead of letting a KeyError/ValueError escape
    mid-merge."""
    if not header:
        return None
    a_unix = header.get("anchor_unix_us")
    a_perf = header.get("anchor_perf_us")
    if a_unix is None and a_perf is None:
        return None
    try:
        a_unix = float(a_unix)
        a_perf = float(a_perf)
    except (TypeError, ValueError):
        raise TraceFileError(
            f"trace file {path} has a corrupt clock anchor in its "
            f"header (anchor_unix_us={header.get('anchor_unix_us')!r}"
            f", anchor_perf_us={header.get('anchor_perf_us')!r})")
    if not (math.isfinite(a_unix) and math.isfinite(a_perf)):
        raise TraceFileError(
            f"trace file {path} has a non-finite clock anchor in "
            f"its header ({a_unix}, {a_perf})")
    return a_unix - a_perf


def _alignment_offsets(
        loaded: Sequence[Tuple[str, Optional[Dict[str, Any]],
                               List[Dict[str, Any]]]]
) -> Tuple[List[float], List[bool]]:
    """The shared alignment core of ``merge_traces`` and
    ``load_events_aligned``: per-file rebase offsets (µs) plus which
    files carried a clock anchor.  All anchored → wall-clock
    offsets; any file anchorless (legacy) → degraded mode, every file
    rebased to its own first event.  Raises :class:`TraceFileError`
    (via :func:`_clock_anchor_offset`) on corrupt anchors."""
    anchors = [_clock_anchor_offset(header, path)
               for path, header, _ in loaded]
    anchored = [off is not None for off in anchors]
    if all(anchored):
        return list(anchors), anchored
    return [
        -min((float(ev["ts"]) for ev in events if "ts" in ev),
             default=0.0)
        for _, _, events in loaded
    ], anchored


def merge_traces(paths: Sequence[str], out_path: str
                 ) -> Dict[str, Any]:
    """Align and merge N per-process trace files into one Chrome
    trace; returns a summary dict (files, events, lanes, offsets).

    Alignment: each file's header anchors its process-local
    ``perf_counter`` timeline to the wall clock, so events are
    rebased as ``ts + (anchor_unix_us - anchor_perf_us)`` — after
    which all files share one axis — then shifted so the earliest
    merged event sits at 0.  When ANY input lacks an anchor
    (headerless legacy trace), wall-clock alignment is impossible, so
    EVERY file degrades to starting at 0 on the merged axis —
    mixing a wall-rebased file with a raw-``perf_counter`` one would
    otherwise scatter the lanes decades apart.  The summary's
    ``aligned`` flag says which mode applied.

    Lanes: every (file, tid) pair maps to a FRESH merged tid, so two
    processes' thread-1 lanes can never collide, and each lane is
    labeled ``host:pid thread-name`` (thread names recovered from
    Chrome ``thread_name`` metadata or JSONL ``thread`` fields).
    Span correlation ids are namespaced per file for the same reason.
    Per-lane nesting is preserved (a uniform per-file shift cannot
    reorder spans within a lane), so ``check_well_nested`` holds on
    the merged trace iff it held on the inputs.

    Shard lanes: events tagged with a scalar ``shard`` arg (the
    partitioned engine's per-shard ``shard_segment`` instants —
    engine/runner.py) are demuxed onto their own
    ``(file, tid, shard)`` lane labeled ``... [shard N]``, so a
    sharded solve reads as one lane per shard in Perfetto instead of
    an interleaved pile on the dispatching host thread.
    """
    if len(paths) < 2:
        raise TraceFileError("trace merge needs at least two files")
    loaded = []
    for path in paths:
        header, events, names = _parse_trace(path)
        loaded.append((path, header, events, names))
    offsets, anchored = _alignment_offsets(
        [(path, header, events)
         for path, header, events, _ in loaded])
    aligned = all(anchored)
    base = min(
        (float(ev["ts"]) + off
         for (_, _, events, _), off in zip(loaded, offsets)
         for ev in events if "ts" in ev),
        default=0.0,
    )
    lane_map: Dict[Tuple[int, Any], int] = {}
    lane_names: Dict[int, str] = {}
    merged: List[Dict[str, Any]] = []
    _ID_STRIDE = 10 ** 9  # far above any single-process span count

    def _lane(fi: int, tid, label: str) -> int:
        key = (fi, tid)
        if key not in lane_map:
            lane_map[key] = len(lane_map) + 1
            lane_names[lane_map[key]] = label
        return lane_map[key]

    for fi, ((path, header, events, names), off) in enumerate(
            zip(loaded, offsets)):
        who = (f"{header.get('host', '?')}:{header.get('pid', '?')}"
               if header else f"file{fi}")
        for ev in events:
            out = dict(ev)
            out["ts"] = float(ev.get("ts", 0.0)) + off - base
            thread = (names.get(ev.get("tid"))
                      or str(ev.get("tid", "?")))
            shard = (ev.get("args") or {}).get("shard")
            if isinstance(shard, (int, str)) and not isinstance(
                    shard, bool):
                out["tid"] = _lane(
                    fi, (ev.get("tid"), "shard", shard),
                    f"{who} {thread} [shard {shard}]")
            else:
                out["tid"] = _lane(fi, ev.get("tid"),
                                   f"{who} {thread}")
            out.pop("thread", None)
            # Correlation ids (top-level in JSONL events, inside args
            # for re-loaded Chrome exports): namespace per file so
            # cross-process id reuse cannot fake a parent link.
            # Integer ids only — foreign Chrome traces (JAX profiler,
            # chrome://tracing async events) carry string ids like
            # "0x42", which pass through untouched rather than crash.
            for holder, id_key, parent_key in (
                    (out, "id", "parent"),
                    (out.get("args") or {}, "span_id", "parent_id")):
                for k in (id_key, parent_key):
                    value = holder.get(k)
                    if isinstance(value, int) and value:
                        holder[k] = value + fi * _ID_STRIDE
            merged.append(out)
    merged.sort(key=lambda e: e["ts"])
    trace_events = [
        {"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
         "args": {"name": name}}
        for tid, name in sorted(lane_names.items())
    ]
    for ev in merged:
        out = {
            "name": ev.get("name"), "cat": ev.get("cat", "default"),
            "ph": ev.get("ph"), "ts": ev["ts"], "pid": 0,
            "tid": ev["tid"], "args": dict(ev.get("args") or {}),
        }
        if ev.get("ph") == "X":
            out["dur"] = ev.get("dur", 0.0)
        else:
            out["s"] = "t"
        if ev.get("id"):
            out["args"].setdefault("span_id", ev["id"])
        if ev.get("parent"):
            out["args"].setdefault("parent_id", ev["parent"])
        trace_events.append(out)
    tmp = f"{out_path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            HEADER_KEY: {
                "version": _HEADER_VERSION,
                "merged_from": [
                    {"path": p, "header": h, "clock_anchor": anch}
                    for (p, h, _, _), anch in zip(loaded, anchored)
                ],
                "aligned": aligned,
            },
        }, f, default=str)
    os.replace(tmp, out_path)
    return {
        "files": len(paths),
        "events": len(merged),
        "lanes": len(lane_names),
        "anchored": sum(anchored),
        "aligned": aligned,
        "span_us": (merged[-1]["ts"] - merged[0]["ts"]
                    if merged else 0.0),
    }


def load_events_aligned(paths: Sequence[str]
                        ) -> List[Dict[str, Any]]:
    """The in-memory form of :func:`merge_traces` for analysis
    commands (``pydcop trace query`` over several per-process
    files): one file loads as-is; several load rebased onto one axis
    via their clock anchors (degrading to per-file zero like merge
    when any input is anchorless) with lanes namespaced per file so
    two processes' thread-1 lanes never collide.  Raises
    :class:`TraceFileError` on unreadable files or corrupt
    anchors."""
    if not paths:
        raise TraceFileError("no trace files given")
    loaded = []
    for path in paths:
        header, events, _ = _parse_trace(path)
        loaded.append((path, header, events))
    if len(loaded) == 1:
        return list(loaded[0][2])
    offsets, _ = _alignment_offsets(loaded)
    # Shift so the earliest event lands at ~0, exactly like
    # merge_traces: wall-clock rebasing alone leaves epoch-scale µs
    # timestamps, which would make the query output (ts_ms) unreadable
    # for precisely the cross-process case this path exists for.
    base = min(
        (float(ev["ts"]) + off
         for (_, _, events), off in zip(loaded, offsets)
         for ev in events if "ts" in ev),
        default=0.0)
    out: List[Dict[str, Any]] = []
    for fi, ((path, header, events), off) in enumerate(
            zip(loaded, offsets)):
        for ev in events:
            row = dict(ev)
            row["ts"] = float(ev.get("ts", 0.0)) + off - base
            row["tid"] = f"{fi}:{ev.get('tid')}"
            out.append(row)
    out.sort(key=lambda e: e["ts"])
    return out


def _per_name_stats(events: Iterable[Dict[str, Any]]
                    ) -> Dict[str, Dict[str, float]]:
    durs: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        if ev.get("ph") == "X":
            durs[ev.get("name") or "?"].append(
                float(ev.get("dur", 0.0)) / 1000.0)
        elif ev.get("ph") == "i":
            durs[ev.get("name") or "?"].append(0.0)
    out = {}
    for name, values in durs.items():
        values.sort()
        out[name] = {
            "count": len(values),
            "total_ms": sum(values),
            "p50_ms": values[len(values) // 2] if values else 0.0,
        }
    return out


def diff_trace_summaries(events_a: Iterable[Dict[str, Any]],
                         events_b: Iterable[Dict[str, Any]],
                         threshold: float = 0.25,
                         min_delta_ms: float = 1.0,
                         ) -> List[Dict[str, Any]]:
    """Per-span-name deltas between two traces (A = baseline, B =
    candidate): count, total and p50 duration on each side, and a
    ``regressed`` flag when B's total grew beyond ``threshold``
    (relative) AND ``min_delta_ms`` (absolute — spans in the noise
    floor never flag).  Span names present on only one side are
    reported with zeros on the other; a name absent from A has no
    defined relative growth, so ``delta_rel`` is None there (NOT
    float('inf'), which json.dumps would emit as the non-JSON token
    ``Infinity``) and only the absolute floor gates its flag.
    Sorted by absolute total delta, largest first."""
    stats_a = _per_name_stats(events_a)
    stats_b = _per_name_stats(events_b)
    rows = []
    for name in sorted(set(stats_a) | set(stats_b)):
        a = stats_a.get(name, {"count": 0, "total_ms": 0.0,
                               "p50_ms": 0.0})
        b = stats_b.get(name, {"count": 0, "total_ms": 0.0,
                               "p50_ms": 0.0})
        delta = b["total_ms"] - a["total_ms"]
        rel = (delta / a["total_ms"] if a["total_ms"] > 0
               else (None if delta > 0 else 0.0))
        rows.append({
            "name": name,
            "count_a": a["count"], "count_b": b["count"],
            "total_ms_a": a["total_ms"], "total_ms_b": b["total_ms"],
            "p50_ms_a": a["p50_ms"], "p50_ms_b": b["p50_ms"],
            "delta_total_ms": delta,
            "delta_rel": rel,
            "regressed": (delta >= min_delta_ms
                          and (rel is None or rel >= threshold)),
        })
    rows.sort(key=lambda r: -abs(r["delta_total_ms"]))
    return rows


def summarize_spans(events: Iterable[Dict[str, Any]],
                    by: str = "name", top: Optional[int] = None
                    ) -> List[Dict[str, Any]]:
    """Aggregate complete spans by ``name`` (or ``cat``): count, total
    / mean / max duration in ms, sorted by total descending.  Instant
    events aggregate with zero duration (their counts still matter —
    fault drops and breaker trips are instants)."""
    agg: Dict[str, List[float]] = defaultdict(lambda: [0, 0.0, 0.0])
    for ev in events:
        if ev.get("ph") not in ("X", "i"):
            continue
        key = ev.get(by) or "?"
        dur_ms = float(ev.get("dur", 0.0)) / 1000.0
        entry = agg[key]
        entry[0] += 1
        entry[1] += dur_ms
        entry[2] = max(entry[2], dur_ms)
    rows = [
        {
            by: key, "count": count, "total_ms": total,
            "mean_ms": total / count if count else 0.0, "max_ms": mx,
        }
        for key, (count, total, mx) in agg.items()
    ]
    rows.sort(key=lambda r: (-r["total_ms"], -r["count"], r[by]))
    return rows[:top] if top else rows


def event_matches_request(ev: Dict[str, Any],
                          trace_id: str) -> bool:
    """True when the event is tagged with this request's trace id —
    either directly (``args.trace_id``, request-scoped events) or as
    a member of a batch (``args.trace_ids``, the dispatch-context
    tag every engine event under a serve dispatch inherits)."""
    args = ev.get("args") or {}
    if args.get("trace_id") == trace_id:
        return True
    ids = args.get("trace_ids")
    return (isinstance(ids, (list, tuple))
            and trace_id in ids)


def query_request(events: Iterable[Dict[str, Any]],
                  trace_id: str) -> Dict[str, Any]:
    """One request's span tree out of a (possibly merged) trace.

    Filters events tagged with ``trace_id`` (see
    :func:`event_matches_request`) and rebuilds their causal tree:
    within each thread lane, spans nest by time containment (the
    per-thread span stack guarantees matched spans on one lane nest
    properly); instants attach to the innermost containing span.
    Lanes are stitched under one synthetic request root ordered by
    time, so a request that crossed threads/processes (submit on an
    HTTP handler, queue+dispatch+engine on the scheduler — rebased
    lanes after a merge) still reads as a single tree.

    Returns ``{trace_id, events, spans, instants, lanes, names,
    well_nested, tree}`` — ``tree`` is a list of root nodes, each
    ``{name, cat, ph, ts_ms, dur_ms, tid, args, children}``;
    ``well_nested`` is False when the matched spans violate per-lane
    nesting (a corrupted or mis-merged trace)."""
    matched = [ev for ev in events
               if ev.get("ph") in ("X", "i")
               and event_matches_request(ev, trace_id)]
    spans = [ev for ev in matched if ev.get("ph") == "X"]
    instants = [ev for ev in matched if ev.get("ph") == "i"]
    try:
        check_well_nested(spans)
        well_nested = True
    except (ValueError, KeyError, TypeError):
        well_nested = False

    def _node(ev: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "name": ev.get("name"),
            "cat": ev.get("cat", "default"),
            "ph": ev.get("ph"),
            "ts_ms": float(ev.get("ts", 0.0)) / 1000.0,
            "dur_ms": float(ev.get("dur", 0.0)) / 1000.0,
            "tid": ev.get("tid"),
            "args": dict(ev.get("args") or {}),
            "children": [],
        }

    roots: List[Dict[str, Any]] = []
    by_tid: Dict[Any, List[Dict[str, Any]]] = defaultdict(list)
    for ev in spans:
        by_tid[ev.get("tid")].append(ev)
    for tid_spans in by_tid.values():
        tid_spans.sort(key=lambda e: (float(e.get("ts", 0.0)),
                                      -float(e.get("dur", 0.0))))
        stack: List[tuple] = []  # (end_ts, node)
        for ev in tid_spans:
            start = float(ev.get("ts", 0.0))
            end = start + float(ev.get("dur", 0.0))
            node = _node(ev)
            while stack and start >= stack[-1][0] - 1.0:
                stack.pop()
            if stack:
                stack[-1][1]["children"].append(node)
            else:
                roots.append(node)
            stack.append((end, node))
    # Instants: innermost containing matched span on the same lane,
    # else a root of their own.
    for ev in instants:
        ts = float(ev.get("ts", 0.0))
        tid = ev.get("tid")
        best = None
        best_span = None

        def _walk(node):
            nonlocal best, best_span
            start = node["ts_ms"] * 1000.0
            end = start + node["dur_ms"] * 1000.0
            if (node["ph"] == "X" and node["tid"] == tid
                    and start - 1.0 <= ts <= end + 1.0):
                span_len = end - start
                if best is None or span_len < best:
                    best = span_len
                    best_span = node
            for child in node["children"]:
                _walk(child)

        for root in roots:
            _walk(root)
        node = _node(ev)
        if best_span is not None:
            best_span["children"].append(node)
        else:
            roots.append(node)
    roots.sort(key=lambda n: n["ts_ms"])
    for root in roots:
        root["children"].sort(key=lambda n: n["ts_ms"])
    return {
        "trace_id": trace_id,
        "events": len(matched),
        "spans": len(spans),
        "instants": len(instants),
        "lanes": len({ev.get("tid") for ev in matched}),
        "names": sorted({ev.get("name") for ev in matched}),
        "well_nested": well_nested,
        "tree": roots,
    }


def check_well_nested(events: Iterable[Dict[str, Any]]) -> None:
    """Raise ``ValueError`` unless, per thread, complete spans form a
    proper nesting (every pair either disjoint or contained).  Spans
    are recorded via a per-thread stack, so a violation means a
    corrupted trace file — ``make trace-demo`` gates on this."""
    by_tid: Dict[Any, List[tuple]] = defaultdict(list)
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ts = float(ev["ts"])
        by_tid[ev.get("tid")].append((ts, ts + float(ev["dur"]), ev))
    eps = 1.0  # µs of timer slack between adjacent spans
    for tid, spans in by_tid.items():
        spans.sort(key=lambda s: (s[0], -s[1]))
        stack: List[tuple] = []
        for start, end, ev in spans:
            while stack and start >= stack[-1][1] - eps:
                stack.pop()
            if stack and end > stack[-1][1] + eps:
                raise ValueError(
                    f"span {ev.get('name')!r} [{start:.0f}, {end:.0f}] "
                    f"on tid {tid} overlaps enclosing span "
                    f"{stack[-1][2].get('name')!r} "
                    f"[{stack[-1][0]:.0f}, {stack[-1][1]:.0f}] "
                    "without nesting"
                )
            stack.append((start, end, ev))

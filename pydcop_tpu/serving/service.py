"""The multi-tenant solve service: queue, binning dispatch, results.

``SolveService`` turns the device engine into a throughput service:
callers :meth:`~SolveService.submit` DCOPs (each compiled on the
submitting thread — malformed problems fail synchronously, and
same-structure requests hit the PR-3 layout cache), a scheduler
thread (serving/scheduler.py) drains the bounded queue, bins requests
by structure signature (serving/binning.py) and dispatches each bin
as ONE vmapped device program (engine/batch.run_stacked, padded up
the bin-size ladder so ragged batch sizes reuse compiled programs).
Results stream back per request with latency accounting; admission
control (serving/admission.py) sheds load at the high-water mark and
opens a circuit breaker on repeated dispatch failure.

Request-plane telemetry (all registered on the process registry, so
the serving front end's ``/metrics`` exposes them):

- ``pydcop_requests_total{status}`` — every submit accounted:
  ``ok`` / ``error`` / ``rejected_queue_full`` /
  ``rejected_unavailable`` / ``rejected_bad_request``;
- ``pydcop_request_latency_seconds`` — submit→result histogram
  (p50/p99 straight off the buckets);
- ``pydcop_serve_queue_depth`` / ``pydcop_serve_batch_occupancy`` —
  live gauges;
- ``pydcop_serve_dispatches_total{kind}`` (``batched``/``solo``) and
  ``pydcop_serve_batched_requests_total`` — the batch-coalescing
  evidence (N same-structure requests in << N dispatches);
- per-batch ``serve_dispatch`` trace spans when tracing is on.

Fault tolerance (docs/resilience.md "Serving & sharding fault
tolerance"):

- **Durable journal + crash recovery** (``journal_dir=``): every
  admitted request is journaled BEFORE ``submit`` returns (the 202 is
  a durable promise), terminal outcomes are journaled too, and
  ``recover=True`` replays accepted-but-unfinished entries through
  the normal queue on start (``serve_replay`` span,
  ``pydcop_serve_replayed_total``) — a kill -9 mid-burst loses zero
  acknowledged requests (tools/serve_smoke.py asserts it).
- **Deadlines** (``submit(..., deadline_s=...)``): the scheduler
  drops already-expired work before binning — terminal state
  ``EXPIRED``, ledger status ``rejected_deadline``, 504 on the wire.
- **Poison isolation**: a failed multi-request bin dispatch BISECTS
  instead of failing wholesale — halves are retried
  (``pydcop_serve_dispatch_retries_total``) until the poison request
  fails alone and its bin-mates succeed; only the isolated singleton
  failure feeds the admission breaker.
- **Graceful drain**: ``stop(drain=True)`` returns a summary dict;
  with a journal, requests still queued at shutdown stay journaled
  as REPLAYABLE instead of being failed (``pydcop serve`` wires this
  to SIGTERM/SIGINT).
"""

import contextlib
import itertools
import logging
import os
import queue
import threading
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine import batch as engine_batch
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.observability import efficiency, flight
from pydcop_tpu.observability.metrics import CycleSnapshotter
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.profiler import profiler
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.ops.dpop import UtilTooLargeError
from pydcop_tpu.serving import binning, journal as journal_mod
from pydcop_tpu.serving.admission import (
    AdmissionController,
    AdmissionPolicy,
    AdmissionRejected,
)

logger = logging.getLogger("pydcop.serving.service")

# Request states (FINISHED / ERROR / EXPIRED are terminal;
# REPLAYABLE is terminal for THIS process only — the journal still
# holds the accepted record, so a --recover restart replays it).
QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
ERROR = "ERROR"
EXPIRED = "EXPIRED"
REPLAYABLE = "REPLAYABLE"


class _DuplicateDelivery(Exception):
    """A caller-supplied request id was delivered again (duplicate
    socket delivery, a router retry after a lost response): resolved
    inside ``_submit`` by acknowledging the ORIGINAL — never an
    error, never a second execution."""


class WidthRejected(ValueError):
    """``algo="dpop"`` on a problem whose UTIL hypercubes bust
    ``ops/dpop.MAX_NODE_ELEMENTS`` even after CEC shrinkage.

    Raised ON THE SUBMITTING THREAD (width is decided from the
    pseudo-tree before any table exists), so the front end turns it
    into a structured 400 ``rejected_width`` — never a dispatch-time
    ``MemoryError`` feeding the admission breaker and a 500."""

    status = "rejected_width"

    def __init__(self, message: str, max_elements: int = 0,
                 cap: int = 0):
        super().__init__(message)
        self.max_elements = int(max_elements)
        self.cap = int(cap)


@dataclass
class SolveRequest:
    """One in-flight problem: compiled form + bookkeeping.

    ``deadline_s`` is a freshness budget relative to ``t_submit``:
    the scheduler refuses to dispatch the request past it (terminal
    state ``EXPIRED``).  ``replayed`` marks requests resurrected from
    the journal by crash recovery (their clock restarts at replay —
    the original submit clock died with the crashed process)."""

    id: str
    dcop: DCOP
    graph: Any
    meta: Any
    params: Dict[str, Any]
    bin: Any
    t_submit: float
    deadline_s: Optional[float] = None
    replayed: bool = False
    # Exact-inference requests (params["algo"] == "dpop") carry their
    # pseudo-tree from the submit-time width check to the dispatch —
    # built once per request, on the submitting thread.
    exact_tree: Any = None
    # Time-ledger breakpoints (observability/efficiency.py): enqueue
    # (submit-thread work ends), dispatch pickup, and the flush-plan
    # wall this request waited through — contiguous with the device
    # and decode intervals measured at dispatch, so the ledger's
    # components sum to the measured end-to-end latency.
    t_enqueue: float = 0.0
    t_dispatch: float = 0.0
    plan_s: float = 0.0
    # Request-scoped causality key: minted at submit, carried through
    # the journal record, queue entry, dispatch context and every
    # span/instant the request touches (docs/observability.md
    # "Tracing a single request").
    trace_id: str = ""
    status: str = QUEUED
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[Dict[str, Any]] = None


class DispatchPlan(NamedTuple):
    """One device dispatch the scheduler should fire for a flush
    (:meth:`SolveService.plan_flush`).  ``envelope``/``lane_d`` both
    None is the exact same-structure path; ``envelope`` (a
    serving/binning.Envelope) mask-pads a heterogeneous group to one
    shape; ``lane_d`` (a domain rung) lane-packs it as a disjoint
    union (engine/batch.run_lane_packed)."""

    reqs: List["SolveRequest"]
    envelope: Optional[Any] = None
    lane_d: Optional[int] = None


class PendingBatch(NamedTuple):
    """One launched-but-uncollected pipelined dispatch
    (:meth:`SolveService.launch_dispatch`): the device is executing
    bin k while the scheduler launches bin k+1 and decodes bin k-1.
    Consumed exactly once by :meth:`SolveService.collect_dispatch`.
    ``t_launch_end`` bounds the overlap measurement — host wall after
    it and before collect was spent on OTHER work while this
    dispatch's device work was in flight."""

    reqs: List["SolveRequest"]
    pending: Any                    # engine_batch.PendingDispatch
    envelope: Optional[Any] = None
    lane_d: Optional[int] = None
    t_launch_end: float = 0.0


class SolveService:
    """Bounded-queue, structure-binned batching solve service.

    Knobs: ``max_queue`` bounds the request queue (also the default
    admission high-water mark), ``batch_window_s`` is how long the
    scheduler lingers after the first request collecting batch-mates,
    ``max_batch`` caps one dispatch, ``bin_sizes`` is the
    padding ladder (engine/batch.DEFAULT_BIN_SIZES when None),
    ``default_params`` overrides the solver defaults
    (serving/binning.DEFAULT_PARAMS) service-wide, ``admission`` the
    backpressure/breaker policy, ``result_keep`` bounds completed-
    result retention (oldest evicted first — a long-lived service must
    not leak every response it ever produced).

    **Envelope batching** (ISSUE 11, on by default): structure bins
    are exact, so diverse traffic degenerates to batch-size-1 — every
    flush's leftover SINGLETON bins are therefore grouped by
    shape-envelope key (serving/binning.envelope_key over
    ``envelope_ladder``) and packed into one mask-padded dispatch when
    the modeled win beats solo dispatch
    (serving/binning.pack_decision: padding waste vs
    ``envelope_overhead_ms`` per dispatch, with the PR-10 portfolio
    cache's measured per-structure times as free priors).  Groups
    whose domain rung is at most ``lane_domain_max`` (and that don't
    request pruning — an edge-major-only kernel) route through
    lane packing instead (engine/batch.run_lane_packed): a disjoint
    union with no per-member shape padding at all.  Results stay
    bit-identical to solo ``api.solve`` either way (mask-padded lanes
    and union members compute exactly the solo messages — battery- and
    smoke-asserted); ``envelope_packing=False`` restores the old
    solo-singleton behavior.

    ``journal_dir`` enables the durable request journal
    (serving/journal.py): acks become crash-durable, and
    ``recover=True`` replays accepted-but-unfinished requests through
    the normal queue on :meth:`start`.  ``journal_sync`` adds an
    fsync per record (machine-crash durability) at a per-request
    latency cost; the default flush already survives a process kill.
    """

    def __init__(self, max_queue: int = 256,
                 batch_window_s: float = 0.02,
                 max_batch: int = 16,
                 bin_sizes: Optional[List[int]] = None,
                 default_params: Optional[Dict[str, Any]] = None,
                 admission: Optional[AdmissionPolicy] = None,
                 result_keep: int = 4096,
                 journal_dir: Optional[str] = None,
                 journal_sync: bool = False,
                 recover: bool = False,
                 envelope_packing: bool = True,
                 envelope_ladder: Optional[
                     binning.EnvelopeLadder] = None,
                 envelope_overhead_ms: Optional[float] = None,
                 lane_pack: bool = True,
                 lane_domain_max: int = 8,
                 pipeline: bool = True,
                 speculate: bool = False,
                 session_max: int = 64,
                 session_segment_cycles: Optional[int] = None,
                 session_checkpoint_every_events: int = 8,
                 session_keep: int = 256,
                 session_certify_after: Optional[float] = None):
        if admission is None:
            admission = AdmissionPolicy(high_water=max_queue)
        self.admission = AdmissionController(admission)
        self.batch_window_s = batch_window_s
        self.max_batch = max(int(max_batch), 1)
        self.bin_sizes = tuple(
            bin_sizes or engine_batch.DEFAULT_BIN_SIZES)
        self.default_params = binning.normalize_params(default_params)
        self.result_keep = result_keep
        self.envelope_packing = bool(envelope_packing)
        self.envelope_ladder = (envelope_ladder
                                or binning.DEFAULT_LADDER)
        self.envelope_overhead_ms = float(
            envelope_overhead_ms if envelope_overhead_ms is not None
            else binning.PACK_OVERHEAD_MS)
        self.lane_pack = bool(lane_pack)
        self.lane_domain_max = int(lane_domain_max)
        # Closed-loop hot path (ISSUE 18): pipelined flush decode
        # (launch bin k+1 while bin k's arrays are still in flight)
        # and speculative envelope compilation (predict-and-AOT-build
        # the programs the traffic will need, off the scheduler
        # thread).  ``--no_pipeline`` / ``--no_speculate`` isolate
        # each piece.
        self.pipeline = bool(pipeline)
        self.speculate = bool(speculate)
        self._speculator = None
        self._scheduler_ident: Optional[int] = None
        # Per-flush caches the planner refreshes at most once per
        # flush: the autotune JSON document (portfolio priors) and
        # the ledger-fitted pack-model constants.
        self._flush_autotune_data: Optional[Dict[str, Any]] = None
        self._flush_constants: Optional[Dict[str, float]] = None
        # Per-structure solve-time priors for the pack decision
        # (portfolio-cache reads memoized — the JSON file must not be
        # re-read per flush).
        self._prior_memo: Dict[str, Optional[float]] = {}
        # Recent pack-vs-solo decisions, replayable surface for tests
        # and /stats.
        self.envelope_decisions: "deque" = deque(maxlen=64)
        self.journal_dir = journal_dir
        self.journal_sync = journal_sync
        self.recover_on_start = recover
        self._journal = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._requests: "OrderedDict[str, SolveRequest]" = OrderedDict()
        # Outcomes recovered from the journal's completed-with-result
        # tail (--recover): rid -> wire-form result dict.  Read-mostly
        # after start(); bounded by journal.COMPLETED_KEEP.
        self._recovered_results: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._scheduler = None
        self._started = False
        # Dispatch ledger (also mirrored into the registry).
        self.dispatches = 0
        self.batched_dispatches = 0
        self.batched_requests = 0
        self.envelope_dispatches = 0
        self.lane_dispatches = 0
        self.envelope_packed_requests = 0
        self.completed = 0
        self.failed = 0
        self.expired = 0
        self.replayed = 0
        self.dispatch_retries = 0
        self.pipelined_dispatches = 0
        self.speculative_hits = 0
        # prune="auto" submits resolved through the portfolio cache.
        self.portfolio_resolved = 0
        self.deduped = 0
        # Exact-inference plane (ISSUE 17): dispatches completed via
        # DpopEngine, and the shared warm-key set that keeps repeat
        # same-signature solves attributed as warm in the jit ledger.
        self.dpop_dispatches = 0
        self._dpop_warm: set = set()
        self.last_stop: Optional[Dict[str, Any]] = None
        reg = metrics_registry
        self._req_total = reg.counter(
            "pydcop_requests_total",
            "Solve-service requests by terminal status")
        self._latency = reg.histogram(
            "pydcop_request_latency_seconds",
            "Submit-to-result latency of solve-service requests")
        self._queue_depth = reg.gauge(
            "pydcop_serve_queue_depth",
            "Solve-service requests waiting in the queue")
        self._occupancy = reg.gauge(
            "pydcop_serve_batch_occupancy",
            "Real-instance fraction of the last dispatched batch")
        self._dispatch_total = reg.counter(
            "pydcop_serve_dispatches_total",
            "Device dispatches by kind (batched = >1 real instance)")
        self._batched_reqs = reg.counter(
            "pydcop_serve_batched_requests_total",
            "Requests that shared their device dispatch with others")
        self._pad_waste = reg.counter(
            "pydcop_serve_padded_lanes_total",
            "Padded (wasted) batch lanes dispatched to the device")
        self._retries = reg.counter(
            "pydcop_serve_dispatch_retries_total",
            "Bisection retry dispatches after a failed bin dispatch")
        self._envelope_total = reg.counter(
            "pydcop_serve_envelope_dispatches_total",
            "Heterogeneous-structure packed dispatches by kind "
            "(envelope = mask-padded stack, lane = disjoint union)")
        self._envelope_decided = reg.counter(
            "pydcop_serve_envelope_decisions_total",
            "Per-flush envelope pack-vs-solo cost decisions by verdict")
        self._envelope_waste_g = reg.gauge(
            "pydcop_serve_envelope_waste",
            "Padded-cell fraction of the last envelope-packed dispatch")
        self._replayed_total = reg.counter(
            "pydcop_serve_replayed_total",
            "Journaled requests replayed through the queue on "
            "crash recovery")
        self._journal_records = reg.counter(
            "pydcop_serve_journal_records_total",
            "Request-journal records appended, by kind")
        # Stateful solve sessions (ISSUE 13, serving/sessions.py):
        # long-lived DynamicMaxSumEngine solves whose scenario events
        # apply between engine segments on the scheduler thread.
        from pydcop_tpu.serving.sessions import SessionManager

        self.sessions = SessionManager(
            self, max_sessions=session_max,
            segment_cycles=session_segment_cycles,
            checkpoint_every_events=session_checkpoint_every_events,
            session_keep=session_keep,
            certify_after=session_certify_after)

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> "SolveService":
        from pydcop_tpu.serving.scheduler import BinScheduler

        if self._started:
            return self
        # Activated like an ObservabilitySession: request-plane detail
        # counters should record while the service runs; the prior
        # state is restored on stop so an embedding process (tests,
        # bench) is left the way it was found.  The XLA cost profiler
        # rides along (one throwaway AOT compile per cache key):
        # without its flops/bytes entries the efficiency plane can
        # report time ledgers but never attainment — and efficiency
        # must be an always-scrapeable signal, not a bench-only one.
        # ``PYDCOP_XLA_PROFILE=0`` still vetoes.
        self._was_active = metrics_registry.active
        metrics_registry.active = True
        self._was_profiling = profiler.enabled
        profiler.enabled = True
        pending = []
        pending_sessions = []
        recovered_results = []
        if self.journal_dir and self._journal is None:
            if self.recover_on_start:
                (self._journal, pending, pending_sessions,
                 recovered_results) = \
                    journal_mod.RequestJournal.recover_full(
                        self.journal_dir, sync=self.journal_sync)
            else:
                self._journal = journal_mod.RequestJournal(
                    self.journal_dir, sync=self.journal_sync)
        if self.speculate and self._speculator is None:
            from pydcop_tpu.serving.speculate import (
                SpeculativeCompiler,
            )

            self._speculator = SpeculativeCompiler(
                bin_sizes=self.bin_sizes)
            self._speculator.start()
        self._scheduler = BinScheduler(
            self, batch_window_s=self.batch_window_s,
            max_batch=self.max_batch)
        self._scheduler.start()
        self._scheduler_ident = self._scheduler.thread_ident()
        self._started = True
        if self._journal is not None:
            # Journal backlog feeds the operator surfaces while the
            # service runs: /healthz (replay debt before a restart)
            # and postmortem bundles (what was pending at the
            # anomaly).  The bound method is kept so stop() can
            # identity-clear exactly this registration.
            self._flight_provider = self.journal_summary
            flight.set_journal_provider(self._flight_provider)
        if recovered_results:
            # The predecessor's journaled outcomes: a client still
            # polling a pre-crash ack gets its 200 from here instead
            # of a 404 (the in-memory result cache died with the
            # process).  Live requests shadow this cache — result()
            # checks ``_requests`` first.
            with self._lock:
                for rec in recovered_results:
                    self._recovered_results[rec["id"]] = (
                        rec.get("result") or {})
        if pending:
            self._replay(pending)
        if pending_sessions:
            # Whole-session replay: engines rebuilt from the open
            # records, warm state restored from the newest checkpoint,
            # journaled-but-unapplied event batches re-applied
            # (serving/sessions.py SessionManager.recover).
            self.sessions.recover(pending_sessions)
        return self

    def stop(self, drain: bool = True,
             timeout: float = 30.0) -> Dict[str, Any]:
        """Stop the scheduler.  ``drain=True`` (default) lets queued
        requests finish first — a service shutdown must not silently
        drop accepted work; ``drain=False`` skips the wait.  Requests
        still queued after the drain window are journaled-REPLAYABLE
        when a journal is active (a ``--recover`` restart picks them
        up; in-process ``result(wait=...)`` waiters are woken with a
        ``REPLAYABLE`` result instead of sleeping out their window),
        and failed with a shutdown error otherwise.

        Returns a drain summary: ``drained`` (requests completed
        between the stop call and the scheduler halt), ``replayable``
        (left in the journal for the next ``--recover`` start) and
        ``failed_pending`` (dropped with an error — journal-less
        services only)."""
        if not self._started:
            return dict(self.last_stop or
                        {"drained": 0, "replayable": 0,
                         "failed_pending": 0, "parked_sessions": 0})
        completed_before = self.completed
        if drain:
            deadline = time.monotonic() + timeout
            while (not self._queue.empty()
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        self._scheduler.shutdown(timeout=timeout)
        self._scheduler = None
        if self._speculator is not None:
            self._speculator.stop()
            self._speculator = None
        self._started = False
        metrics_registry.active = self._was_active
        profiler.enabled = getattr(self, "_was_profiling", False)
        # Anything still queued (drain=False, drain timeout, or a
        # submit that raced the shutdown): journaled services leave it
        # REPLAYABLE — the accepted record survives, a --recover
        # restart replays it — journal-less services fail it.  The
        # queue may also hold the scheduler's unconsumed shutdown
        # sentinel — skip anything that isn't a request.
        failed_pending = 0
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if not isinstance(req, SolveRequest):
                # Queued session work dies with the queue (the
                # session itself is parked below); wake any PATCH
                # waiter blocked on it.
                done = getattr(req, "done", None)
                if done is not None:
                    req.error = "service stopped"
                    done.set()
                continue
            if self._journal is not None:
                logger.info("request %s left journaled-replayable "
                            "at shutdown", req.id)
            else:
                failed_pending += 1
                self._finish_error(req,
                                   "service stopped before dispatch")
        # Park open sessions AFTER the scheduler halted (their
        # engines are safe to touch) and BEFORE the journal closes:
        # journaled sessions checkpoint their warm state + stay
        # REPLAYABLE for --recover, journal-less ones fail.
        parked_sessions = self.sessions.park_all()
        replayable = 0
        if self._journal is not None:
            # Identity-guarded: never strip a sibling journaled
            # service's registration.
            provider = getattr(self, "_flight_provider", None)
            if provider is not None:
                flight.clear_journal_provider(provider)
            # Every accepted-but-not-terminal request — whether still
            # queued or caught mid-collection in the scheduler — has
            # its accepted record on disk and no completion: the next
            # --recover start replays exactly this set.
            with self._lock:
                replayable_reqs = [
                    r for r in self._requests.values()
                    if not r.done.is_set()]
            replayable = len(replayable_reqs)
            self._journal.close()
            self._journal = None
            # Wake in-process waiters: a result(wait=...) caller must
            # not sleep its full window for an answer this process can
            # no longer produce.  The journal keeps only the accepted
            # record — REPLAYABLE is terminal for this process, not
            # for the request.
            for req in replayable_reqs:
                req.result = {
                    "id": req.id, "status": REPLAYABLE,
                    "error": "service stopped before dispatch; "
                             "journaled for --recover replay",
                }
                req.status = REPLAYABLE
                req.done.set()
        self.last_stop = {
            "drained": self.completed - completed_before,
            "replayable": replayable,
            "failed_pending": failed_pending,
            "parked_sessions": parked_sessions,
        }
        return dict(self.last_stop)

    def __enter__(self) -> "SolveService":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- request plane ------------------------------------------------- #

    def submit(self, dcop: DCOP,
               params: Optional[Dict[str, Any]] = None,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> str:
        """Admit, compile and enqueue one problem; returns the request
        id.  Raises :class:`~pydcop_tpu.serving.admission.
        AdmissionRejected` (429/503 at the front end) on backpressure
        and ``ValueError`` (400) on malformed problems/parameters.

        ``deadline_s`` (optional, seconds from now): the scheduler
        refuses to dispatch the request past its deadline — terminal
        ``EXPIRED`` (504 on the wire, ``rejected_deadline`` in the
        ledger) instead of burning device time on an answer nobody is
        waiting for.

        With a journal, the accepted record reaches the OS before
        this returns — the id this hands back survives a process
        kill.

        Every submit mints a ``trace_id`` (returned alongside the id
        over the wire, journaled with the accepted record, stamped on
        every span the request later touches) — ``pydcop trace query
        --request <trace_id>`` reconstructs the request's span tree
        from a trace file.  A caller-supplied ``trace_id`` (the fleet
        router's wire-propagated context, ISSUE 20) is adopted
        instead, so this replica's spans nest under the router's
        admission trace in the fleet collector.

        Compilation happens HERE, on the submitting thread: structure
        errors surface synchronously, concurrent clients compile in
        parallel, and the scheduler thread stays dedicated to device
        dispatch.  Same-structure submissions hit the PR-3 layout
        cache, so the steady-state compile cost is the cost-table
        fill."""
        if not self._started:
            raise RuntimeError("SolveService is not started")
        t_submit = time.perf_counter()
        trace_id = trace_id or uuid.uuid4().hex[:16]
        if not tracer.active:
            return self._submit(dcop, params, request_id, deadline_s,
                                t_submit, trace_id)
        with tracer.span("serve_submit", "serving",
                         trace_id=trace_id):
            return self._submit(dcop, params, request_id, deadline_s,
                                t_submit, trace_id)

    def _submit(self, dcop: DCOP, params, request_id, deadline_s,
                t_submit: float, trace_id: str) -> str:
        if request_id is not None:
            # Submit is IDEMPOTENT on caller-supplied ids (the fleet
            # router mints one per request and, after an ambiguous
            # forward failure, retries against this same replica): a
            # re-delivery — duplicate on the wire, a resend after the
            # response was lost, even across a restart (the journal
            # feeds _recovered_results; replay keeps original ids) —
            # acknowledges the ORIGINAL instead of executing twice or
            # rejecting.  Internally-minted ids (request_id=None)
            # skip this: a fresh ``r<N>`` colliding with a recovered
            # result would falsely swallow a brand-new request.
            with self._lock:
                known = (request_id in self._requests
                         or request_id in self._recovered_results)
                if known:
                    self.deduped += 1
            if known:
                self._req_total.inc(status="deduped")
                # Telemetry-visible dedupe: the fleet forensics tree
                # proves "N deliveries, one execute" from this
                # instant alone (it carries the router's propagated
                # trace_id, same as the winning delivery's spans).
                if tracer.active:
                    tracer.instant("serve_dedupe", "serving",
                                   request=request_id,
                                   trace_id=trace_id)
                return request_id
        try:
            self.admission.admit(self._queue.qsize())
        except AdmissionRejected as rejection:
            status = ("rejected_queue_full"
                      if rejection.http_status == 429
                      else "rejected_unavailable")
            self._req_total.inc(status=status)
            raise
        # Everything below is the caller's fault when it raises
        # (unknown/bad-typed params, malformed problem, duplicate id,
        # bad deadline -> 400 at the front end): still a ledger
        # entry, so pydcop_requests_total reconciles against
        # client-side counts even when clients misbehave.
        try:
            if deadline_s is not None:
                deadline_s = float(deadline_s)
                if not deadline_s > 0:
                    raise ValueError(
                        f"deadline_s must be > 0, got {deadline_s}")
            merged = dict(self.default_params)
            if params:
                merged.update(params)
            merged = binning.normalize_params(merged)
            graph, meta = compile_dcop(
                dcop, noise_level=merged["noise"])
            if merged["prune"] == "auto":
                # Consume the portfolio racer's persisted decision
                # for this structure (engine/autotune): pruned maxsum
                # when it won the race, dense otherwise.  Replay
                # only — the serving hot path never measures; a cache
                # miss resolves dense.  Resolved BEFORE the bin key,
                # so a bin is homogeneous in the compiled program it
                # dispatches.
                from pydcop_tpu.engine.autotune import (
                    cached_portfolio_choice,
                    graph_shape_key,
                    portfolio_key,
                )

                choice = cached_portfolio_choice(
                    portfolio_key(graph_shape_key(graph)))
                merged["prune"] = 1 if choice == "maxsum_prune" else 0
                with self._lock:
                    self.portfolio_resolved += 1
            exact_tree = None
            if merged["algo"] == "dpop":
                exact_tree = self._check_width(dcop)
            req = SolveRequest(
                id=request_id or f"r{next(self._ids)}",
                dcop=dcop, graph=graph, meta=meta, params=merged,
                bin=binning.bin_key(graph, merged),
                t_submit=t_submit, deadline_s=deadline_s,
                trace_id=trace_id, exact_tree=exact_tree,
            )
            with self._lock:
                if req.id in self._requests:
                    if request_id is not None:
                        # Two deliveries raced past the early dedupe
                        # check: the one that lost the insert race is
                        # a duplicate, not an error.
                        raise _DuplicateDelivery()
                    raise ValueError(
                        f"duplicate request id {req.id!r}")
                self._requests[req.id] = req
                self._prune_locked()
        except _DuplicateDelivery:
            with self._lock:
                self.deduped += 1
            self._req_total.inc(status="deduped")
            if tracer.active:
                tracer.instant("serve_dedupe", "serving",
                               request=request_id, trace_id=trace_id)
            return request_id
        except WidthRejected:
            # Its own ledger status: an over-wide exact request is a
            # capacity verdict about the problem, not a malformed
            # payload — operators watching rejected_bad_request for
            # client bugs must not see width verdicts in that count.
            self._req_total.inc(status="rejected_width")
            raise
        except Exception:
            self._req_total.inc(status="rejected_bad_request")
            raise
        if self._journal is not None:
            # BEFORE the queue and before the caller can ack: the 202
            # must never outlive the journal record.  A failed append
            # fails the submit — a durability promise the service
            # cannot keep must not be made.
            try:
                from pydcop_tpu.dcop.yamldcop import dcop_yaml

                self._journal.append(journal_mod.accepted_record(
                    req.id, dcop_yaml(dcop), req.params,
                    deadline_s=deadline_s, t_submit=t_submit,
                    trace_id=trace_id))
                self._journal_records.inc(kind="accepted")
            except Exception as exc:
                with self._lock:
                    self._requests.pop(req.id, None)
                self._req_total.inc(status="error")
                raise RuntimeError(
                    f"request journal append failed: {exc}") from exc
        # Published BEFORE the enqueue: once the request is in the
        # queue the scheduler may dispatch (and even finish) it ahead
        # of this thread's next line, and SSE clients are promised
        # accepted → dispatched → finished in order.
        self._publish_lifecycle("accepted", req)
        req.t_enqueue = time.perf_counter()
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            # qsize raced past the high-water check: same contract as
            # an admission rejection, never a blocking put.  The
            # journal must agree the request is terminal — without
            # the completion record a --recover restart would replay
            # a request its client saw rejected.
            with self._lock:
                self._requests.pop(req.id, None)
            req.status = ERROR
            self._journal_done(req)
            self._req_total.inc(status="rejected_queue_full")
            # The stream already saw "accepted": close the lifecycle
            # out rather than leaving watchers waiting forever.
            self._publish_lifecycle("error", req)
            raise QueueFullRace(
                f"request queue full ({self._queue.maxsize})")
        self._queue_depth.set(self._queue.qsize())
        return req.id

    def _replay(self, records: List[Dict[str, Any]]) -> None:
        """Re-enqueue journaled accepted-but-unfinished requests
        through the normal queue (crash recovery).  Replayed requests
        keep their original ids (clients poll the id they were acked
        with) and skip admission — they were admitted by the previous
        process; their accepted records already survive in the
        compacted journal, so nothing is re-journaled here.  A record
        that no longer compiles is failed (journaled terminal) rather
        than dropped."""
        from pydcop_tpu.dcop.yamldcop import load_dcop

        # Replay start is black-box-worthy: the bundle shows what the
        # crashed predecessor left behind (and the tail will show
        # whether the replay itself went wrong).
        flight.trigger("journal_replay", n_pending=len(records))
        span = (tracer.span("serve_replay", "serving",
                            n_pending=len(records))
                if tracer.active else None)
        replayed = 0
        with (span if span is not None else contextlib.nullcontext()):
            for rec in records:
                rid = rec.get("id")
                try:
                    dcop = load_dcop(rec["dcop"])
                    merged = binning.normalize_params(
                        rec.get("params") or {})
                    graph, meta = compile_dcop(
                        dcop, noise_level=merged["noise"])
                    # The deadline clock restarts at replay: the
                    # original submit clock died with the crashed
                    # process, and expiring everything on principle
                    # would turn recovery into a mass 504.
                    req = SolveRequest(
                        id=rid, dcop=dcop, graph=graph, meta=meta,
                        params=merged,
                        bin=binning.bin_key(graph, merged),
                        t_submit=time.perf_counter(),
                        deadline_s=rec.get("deadline_s"),
                        replayed=True,
                        # Keep the pre-crash causality key (pre-PR-9
                        # journals have none: mint fresh).
                        trace_id=(rec.get("trace_id")
                                  or uuid.uuid4().hex[:16]),
                    )
                    with self._lock:
                        self._requests[req.id] = req
                    # Replays re-enter the documented lifecycle from
                    # the top: an SSE client that creates its
                    # per-request state on "accepted" must see
                    # replayed requests too.  Before the put, like
                    # submit() — the scheduler may dispatch first.
                    self._publish_lifecycle("accepted", req)
                    req.t_enqueue = time.perf_counter()
                    self._queue.put(req, timeout=30.0)
                except Exception as exc:  # noqa: BLE001 — one bad
                    # record must not abort the rest of the replay.
                    logger.warning("journal replay failed for %s: %s",
                                   rid, exc)
                    with self._lock:
                        req = self._requests.get(rid)
                    if req is not None:
                        self._finish_error(
                            req, f"journal replay failed: {exc}")
                    elif self._journal is not None and rid:
                        # No request object to fail (the yaml itself
                        # would not load): journal the terminal
                        # directly so the record cannot replay
                        # forever.
                        try:
                            self._journal.append(
                                journal_mod.completed_record(
                                    rid, ERROR, result={
                                        "id": rid, "status": ERROR,
                                        "error": ("journal replay "
                                                  f"failed: {exc}"),
                                    }))
                            self._journal_records.inc(kind="completed")
                        except Exception:
                            logger.warning(
                                "could not journal replay failure "
                                "for %s", rid)
                        self._req_total.inc(status="error")
                    continue
                replayed += 1
                if tracer.active:
                    tracer.instant("serve_replay_request", "serving",
                                   id=rid, trace_id=req.trace_id)
        self.replayed += replayed
        if replayed:
            self._replayed_total.inc(replayed)
            logger.info("journal recovery replayed %d request(s)",
                        replayed)
        self._queue_depth.set(self._queue.qsize())

    def record_bad_request(self) -> None:
        """Ledger a client error rejected before :meth:`submit` could
        run (the front end validates wire-level fields like
        ``timeout`` first) — the request ledger must reconcile against
        client-side counts on every path."""
        self._req_total.inc(status="rejected_bad_request")

    def result(self, request_id: str,
               wait: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """The request's result dict, or None while pending.  With
        ``wait`` (seconds), block up to that long for completion.
        Ids finished by a crashed predecessor resolve from the
        recovered-result cache (--recover).  Raises ``KeyError`` for
        unknown ids."""
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                recovered = self._recovered_results.get(request_id)
        if req is None:
            if recovered is not None:
                return dict(recovered)
            raise KeyError(request_id)
        if wait:
            req.done.wait(wait)
        return req.result if req.done.is_set() else None

    def status(self, request_id: str) -> str:
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                recovered = self._recovered_results.get(request_id)
        if req is None:
            if recovered is not None:
                return recovered.get("status", ERROR)
            raise KeyError(request_id)
        return req.status

    def trace_id(self, request_id: str) -> str:
        """The request's causality key (the handle ``pydcop trace
        query --request`` takes).  Raises ``KeyError`` for unknown
        ids."""
        with self._lock:
            req = self._requests.get(request_id)
            if req is None:
                recovered = self._recovered_results.get(request_id)
        if req is None:
            if recovered is not None and recovered.get("trace_id"):
                return recovered["trace_id"]
            raise KeyError(request_id)
        return req.trace_id

    def _prune_locked(self):
        """Evict oldest COMPLETED results past result_keep (pending
        requests are never evicted — their clients still hold the
        id).  Amortized O(excess), not a full-table scan: the table
        is insertion-ordered, so eviction pops completed entries off
        the front, rotating still-pending heads to the back (each
        entry rotates at most once per call, bounding the loop even
        when everything old is still pending)."""
        excess = len(self._requests) - self.result_keep
        if excess <= 0:
            return
        rotations = 0
        while excess > 0 and rotations < len(self._requests):
            rid = next(iter(self._requests))
            if self._requests[rid].done.is_set():
                del self._requests[rid]
                excess -= 1
            else:
                self._requests.move_to_end(rid)
                rotations += 1

    # -- flush planning (called by the scheduler thread) --------------- #

    def plan_flush(self, bins: Dict[Any, List[SolveRequest]]
                   ) -> List[DispatchPlan]:
        """Turn one coalescing window's bins into dispatch plans.

        Multi-request bins keep the exact same-structure path
        unchanged (identical shapes, zero padding).  Leftover
        SINGLETON bins — exactly the population structure binning
        cannot batch — are grouped by the coarser envelope tier:
        same solver params + same shape envelope
        (serving/binning.envelope_key), or same domain rung for the
        lane route (the disjoint union accepts any variable/factor
        counts, so lane groups only need the domain and params to
        agree).  Each group of >= 2 goes through the
        :func:`~pydcop_tpu.serving.binning.pack_decision` cost model —
        packed only when the modeled dispatch-overhead saving beats
        the padding waste — and losing groups fall back to solo
        dispatches, so a pathological group can never be slower than
        the old behavior by more than the model's error.

        The planning wall is stamped on every request in the flush
        (``plan_s``) — each of them waited through it, so it is a real
        component of each one's latency ledger (the ``plan`` column of
        where-the-time-went).

        Planner crashes degrade HERE, once per flush: planning is an
        optimization, never a correctness dependency, so an exception
        logs ONE traceback and falls back to the old one-plan-per-bin
        behavior for the whole flush (the scheduler's per-chunk guard
        stays the last line of defense)."""
        t_plan = time.perf_counter()
        self._refresh_flush_caches()
        try:
            return self._plan_flush(bins)
        except Exception:  # noqa: BLE001 — degrade, don't crash
            logger.exception(
                "flush planning crashed; dispatching per bin")
            return [DispatchPlan(list(bins[k]))
                    for k in sorted(bins, key=lambda k: -len(bins[k]))]
        finally:
            plan_s = time.perf_counter() - t_plan
            for reqs in bins.values():
                for req in reqs:
                    req.plan_s = plan_s

    def _refresh_flush_caches(self) -> None:
        """Once-per-flush reads of the autotune surfaces the planner
        consults per GROUP otherwise: the shape-cache JSON document
        (portfolio priors for structures not yet memoized) and the
        ledger-fitted pack-model constants (tentpole c — cold start
        falls back to the compiled-in defaults via ``None``)."""
        from pydcop_tpu.engine import autotune

        try:
            self._flush_autotune_data = autotune._load_cache(
                autotune.cache_path())
        except Exception:  # noqa: BLE001 — priors are an optimization
            self._flush_autotune_data = None
        self._flush_constants = None
        if autotune.pack_fit_enabled():
            try:
                fitted = autotune.fitted_pack_constants(
                    efficiency.backend_name())
                if (fitted
                        and self.envelope_overhead_ms
                        != binning.PACK_OVERHEAD_MS):
                    # An operator-set (or test-forced) dispatch
                    # overhead must not be silently overridden by the
                    # fitted one — only the MODEL constants apply.
                    fitted = {k: v for k, v in fitted.items()
                              if k != "overhead_ms"}
                self._flush_constants = fitted or None
            except Exception:  # noqa: BLE001
                self._flush_constants = None

    def _plan_flush(self, bins: Dict[Any, List[SolveRequest]]
                    ) -> List[DispatchPlan]:
        plans: List[DispatchPlan] = []
        singles: List[SolveRequest] = []
        for key in sorted(bins, key=lambda k: -len(bins[k])):
            reqs = bins[key]
            if reqs[0].params.get("algo") == "dpop":
                # Exact-inference bins never enter envelope/lane
                # packing: DPOP batches WITHIN each problem (the
                # level-batched signature buckets), and cross-problem
                # stacking has no meaning for a tree sweep.
                plans.append(DispatchPlan(list(reqs)))
            elif len(reqs) > 1 or not self.envelope_packing:
                plans.append(DispatchPlan(list(reqs)))
            else:
                singles.append(reqs[0])
        if len(singles) == 1:
            self._observe_for_speculation(singles[0], count=1)
            plans.append(DispatchPlan(singles))
            return plans
        groups: Dict[Any, List[SolveRequest]] = {}
        for req in singles:
            env = binning.envelope_key(req.graph,
                                       self.envelope_ladder)
            params_part = req.bin[1]
            lane_ok = (self.lane_pack
                       and env.d_env <= self.lane_domain_max
                       and not req.params.get("prune"))
            gkey = (("lane", env.d_env, params_part) if lane_ok
                    else ("envelope", env, params_part))
            groups.setdefault(gkey, []).append(req)
        for gkey, group in groups.items():
            self._observe_for_speculation(group[0], count=len(group))
            # Decide per max_batch CHUNK, not per group: the
            # scheduler dispatches at most max_batch requests per
            # device call, so a 20-member group runs as 16+4 — the
            # cost model must price the dispatches that will actually
            # execute, or borderline verdicts are computed against a
            # shape that never runs.
            for i in range(0, len(group), self.max_batch):
                reqs = group[i:i + self.max_batch]
                if len(reqs) == 1:
                    plans.append(DispatchPlan(reqs))
                    continue
                # Lane groups are keyed by the domain RUNG (so
                # near-sized domains coalesce) but packed at the
                # chunk's exact max domain — the union's shapes are
                # ladder-bounded by row/var rounding regardless, and
                # rounding the domain would charge every member the
                # rung's hypercube blowup.
                shape = (max(r.graph.dmax for r in reqs)
                         if gkey[0] == "lane" else gkey[1])
                decision = self._pack_decision(gkey[0], shape, reqs)
                if not decision["packed"]:
                    plans.extend(DispatchPlan([r]) for r in reqs)
                    continue
                if gkey[0] == "lane":
                    plans.append(DispatchPlan(reqs, lane_d=shape))
                else:
                    plans.append(DispatchPlan(reqs, envelope=shape))
        return plans

    def _observe_for_speculation(self, req: SolveRequest,
                                 count: int) -> None:
        """Feed the arrival histogram (tentpole b): one cheap
        ``observe`` per envelope group per flush — the speculator
        predicts the bin rungs this structure's traffic will need
        next and AOT-builds them off-thread.  Never raises into the
        planner."""
        if self._speculator is None:
            return
        if req.params.get("algo") == "dpop":
            return
        try:
            env = binning.envelope_key(req.graph,
                                       self.envelope_ladder)
            self._speculator.observe(req.graph, env, req.params,
                                     count)
        except Exception:  # noqa: BLE001 — speculation is optional
            pass

    def _pack_decision(self, kind: str, shape,
                       reqs: List[SolveRequest]) -> Dict[str, Any]:
        """Model one group's pack-vs-solo choice and record it (the
        bounded ``envelope_decisions`` log, /stats, and the decision
        counter) so the choice is replayable and auditable."""
        real = [binning.graph_cells(r.graph) for r in reqs]
        if kind == "lane":
            packed_total = binning.lane_union_cells(
                [r.graph for r in reqs], shape)
            label = f"lane_d{shape}"
        else:
            # Stacked envelope: the batch pads up the bin-size ladder,
            # and every lane (padding lanes included) is a full
            # envelope's worth of cells.
            packed_total = (
                engine_batch.bin_size_for(len(reqs), self.bin_sizes)
                * binning.envelope_cells(shape))
            label = binning.envelope_label(shape)
        priors, sources = [], []
        for r, cells in zip(reqs, real):
            ms, src = self._solve_prior(r, cells)
            priors.append(ms)
            sources.append(src)
        decision = binning.pack_decision(
            real, priors, packed_total,
            max_cycles=reqs[0].params["max_cycles"],
            overhead_ms=self.envelope_overhead_ms,
            constants=self._flush_constants)
        decision.update({
            "kind": kind,
            "label": label,
            "prior_ms": [round(p, 4) for p in priors],
            "prior_sources": sources,
        })
        # Locked: stats() snapshots this deque from other threads,
        # and an unguarded append (maxlen eviction mutates too) can
        # raise mid-iteration there.
        with self._lock:
            self.envelope_decisions.append(decision)
        self._envelope_decided.inc(
            verdict="packed" if decision["packed"] else "solo")
        return decision

    def _solve_prior(self, req: SolveRequest, real_cells: int):
        """Per-structure solo solve-time prior: the PR-10 portfolio
        cache's measured race time when one exists for this structure
        (memoized — one JSON read per structure per process), the
        cells*cycles model otherwise."""
        from pydcop_tpu.engine.autotune import (
            PORTFOLIO_RACE_CYCLES,
            cached_portfolio_timing_ms,
            graph_shape_key,
            portfolio_key,
        )

        portfolio_ms = None
        try:
            skey = graph_shape_key(req.graph)
            if skey in self._prior_memo:
                portfolio_ms = self._prior_memo[skey]
            else:
                # The flush-preloaded JSON document (one disk read
                # per flush, not one per unmemoized group member).
                portfolio_ms = cached_portfolio_timing_ms(
                    portfolio_key(skey),
                    data=self._flush_autotune_data)
                self._prior_memo[skey] = portfolio_ms
        except Exception:  # noqa: BLE001 — a prior is an optimization
            portfolio_ms = None
        return binning.solve_prior_ms(
            real_cells, req.params["max_cycles"], portfolio_ms,
            race_cycles=PORTFOLIO_RACE_CYCLES,
            constants=self._flush_constants)

    # -- dispatch plane (called by the scheduler thread) --------------- #

    def dispatch(self, reqs: List[SolveRequest],
                 envelope=None, lane_d: Optional[int] = None) -> None:
        """Solve one same-bin batch in a single device dispatch and
        complete every request in it.

        An engine failure on a MULTI-request batch does not fail the
        batch wholesale: the bin is BISECTED and each half retried
        (``pydcop_serve_dispatch_retries_total``), recursively, until
        the poison request fails ALONE and its bin-mates succeed —
        log-bounded (at most ``2·n - 1`` dispatches for one poison
        request in a bin of n).  Only the isolated singleton failure
        feeds the admission breaker, so one poison client cannot open
        the circuit for a healthy engine — while a genuinely down
        engine still fails every singleton and trips it."""
        t_dequeue = time.perf_counter()
        for req in reqs:
            req.status = RUNNING
            req.t_dispatch = t_dequeue
            if tracer.active:
                # The queue wait started on the submitting thread and
                # ended here on the scheduler thread: record it
                # retroactively from its explicit endpoints so the
                # request tree shows time-in-queue as a real span.
                tracer.complete(
                    "serve_queued", "serving",
                    t0=req.t_submit, t1=t_dequeue,
                    trace_id=req.trace_id, request=req.id)
            self._publish_lifecycle("dispatched", req)
        self._queue_depth.set(self._queue.qsize())
        self._dispatch_attempt(reqs, retry_depth=0,
                               envelope=envelope, lane_d=lane_d)

    def launch_dispatch(self, reqs: List[SolveRequest],
                        envelope=None, lane_d: Optional[int] = None,
                        ) -> Optional[PendingBatch]:
        """Pipelined dispatch front half (ISSUE 18 tentpole a): issue
        the device call for this batch WITHOUT waiting for its
        results (JAX async dispatch) so the scheduler can launch the
        next bin / decode the previous one while the device works.

        Returns a :class:`PendingBatch` to hand to
        :meth:`collect_dispatch`, or None when this batch must go
        through the synchronous :meth:`dispatch` instead — pipelining
        disabled, a DPOP bin (the exact engine owns its own batching),
        a test double stubbing the device call (``_run_batch`` /
        ``dispatch`` overridden: the stub IS the contract under test),
        a cold program (the compile must be timed and attributed on
        the synchronous path), or a launch failure (the synchronous
        path owns error isolation and bisection)."""
        if not self.pipeline:
            return None
        params = reqs[0].params
        if params.get("algo") == "dpop":
            return None
        if (type(self)._run_batch is not SolveService._run_batch
                or "_run_batch" in self.__dict__
                or type(self).dispatch is not SolveService.dispatch
                or "dispatch" in self.__dict__):
            return None
        graphs = [r.graph for r in reqs]
        t_dequeue = time.perf_counter()
        try:
            if lane_d is not None:
                pending = engine_batch.launch_lane_packed(
                    graphs,
                    max_cycles=params["max_cycles"],
                    damping=params["damping"],
                    damping_nodes=params["damping_nodes"],
                    stability=params["stability"],
                    d_env=lane_d,
                    ladder=binning.UNION_LADDER,
                )
            else:
                pending = engine_batch.launch_stacked(
                    graphs,
                    max_cycles=params["max_cycles"],
                    damping=params["damping"],
                    damping_nodes=params["damping_nodes"],
                    stability=params["stability"],
                    pad_to_bins=self.bin_sizes,
                    prune=bool(params.get("prune", 0)),
                    envelope=envelope,
                )
        except Exception as exc:  # noqa: BLE001 — sync path retries
            logger.debug("pipelined launch failed (%s); falling back "
                         "to the synchronous path", exc)
            return None
        if pending is None:
            return None
        for req in reqs:
            req.status = RUNNING
            req.t_dispatch = t_dequeue
            if tracer.active:
                tracer.complete(
                    "serve_queued", "serving",
                    t0=req.t_submit, t1=t_dequeue,
                    trace_id=req.trace_id, request=req.id)
            self._publish_lifecycle("dispatched", req)
        self._queue_depth.set(self._queue.qsize())
        self.pipelined_dispatches += 1
        return PendingBatch(reqs, pending, envelope, lane_d,
                            time.perf_counter())

    def collect_dispatch(self, pb: PendingBatch) -> None:
        """Pipelined dispatch back half: block on the launched device
        work, then run the SAME decode/terminal tail as the
        synchronous path.  Never raises: a collect failure re-runs
        the batch through the synchronous dispatch attempt (the
        results are deterministic, so re-execution is safe, and the
        synchronous path owns bisection/breaker semantics)."""
        t_collect0 = time.perf_counter()
        reqs = pb.reqs
        ctx = (tracer.context(
            trace_ids=[r.trace_id for r in reqs])
            if tracer.active else contextlib.nullcontext())
        with ctx:
            span = (tracer.span(
                "serve_dispatch", "serving",
                bin=binning.bin_label(reqs[0].bin),
                n_real=len(reqs),
                packing=("lane" if pb.lane_d is not None else
                         "envelope" if pb.envelope is not None else
                         "structure"),
                retry_depth=0, pipelined=True)
                if tracer.active else None)
            try:
                with (span if span is not None
                      else contextlib.nullcontext()):
                    if pb.pending.kind == "lane":
                        values, cycles, batch_result = \
                            engine_batch.collect_lane_packed(
                                pb.pending)
                    else:
                        values, cycles, batch_result = \
                            engine_batch.collect_stacked(pb.pending)
                    if span is not None:
                        span.args["batch_size"] = \
                            batch_result.metrics["batch_size"]
                        span.args["pad_fraction"] = \
                            batch_result.metrics["pad_fraction"]
            except Exception as exc:  # noqa: BLE001
                logger.warning(
                    "pipelined collect failed (%d requests): %s; "
                    "re-dispatching synchronously", len(reqs), exc)
                self._dispatch_attempt(reqs, retry_depth=0,
                                       envelope=pb.envelope,
                                       lane_d=pb.lane_d)
                return
            t_dev1 = time.perf_counter()
            # Overlap accounting: host wall between launch-done and
            # collect-start was spent on other dispatches' work while
            # this one's device work was in flight, clamped to the
            # dispatch's own execute wall.
            run_s = float(batch_result.metrics.get(
                "run_time_s", batch_result.time_s))
            overlap = min(max(t_collect0 - pb.t_launch_end, 0.0),
                          max(run_s, 0.0))
            efficiency.tracker.record_overlap(overlap, run_s)
            self._complete_batch(reqs, batch_result, values, cycles,
                                 pb.pending.t_launch, t_dev1)

    def _dispatch_attempt(self, reqs: List[SolveRequest],
                          retry_depth: int,
                          envelope=None,
                          lane_d: Optional[int] = None) -> None:
        if not tracer.active:
            return self._dispatch_attempt_inner(
                reqs, retry_depth, envelope=envelope, lane_d=lane_d)
        # Thread-bound context: every span/instant recorded under
        # this dispatch — serve_dispatch itself, the engine_segment
        # inside run_stacked, jit_compile, shard instants — carries
        # the batch's trace_ids without the engine knowing about
        # requests.  `pydcop trace query --request ID` matches on it.
        with tracer.context(trace_ids=[r.trace_id for r in reqs]):
            return self._dispatch_attempt_inner(
                reqs, retry_depth, envelope=envelope, lane_d=lane_d)

    def _dispatch_attempt_inner(self, reqs: List[SolveRequest],
                                retry_depth: int,
                                envelope=None,
                                lane_d: Optional[int] = None) -> None:
        params = reqs[0].params
        span = (tracer.span(
            "serve_dispatch", "serving",
            bin=binning.bin_label(reqs[0].bin),
            n_real=len(reqs),
            packing=("lane" if lane_d is not None else
                     "envelope" if envelope is not None else
                     "structure"),
            retry_depth=retry_depth) if tracer.active else None)
        t_dev0 = time.perf_counter()
        try:
            with (span if span is not None
                  else contextlib.nullcontext()):
                if envelope is None and lane_d is None:
                    # Positional call kept for the exact path: test
                    # doubles and the overload smoke stub
                    # _run_batch(reqs, params).
                    values, cycles, batch_result = self._run_batch(
                        reqs, params)
                else:
                    values, cycles, batch_result = self._run_batch(
                        reqs, params, envelope=envelope,
                        lane_d=lane_d)
                if span is not None:
                    span.args["batch_size"] = \
                        batch_result.metrics["batch_size"]
                    span.args["pad_fraction"] = \
                        batch_result.metrics["pad_fraction"]
        except UtilTooLargeError as exc:
            # Width bust discovered only at dispatch (the submit-time
            # gate passed on CEC-shrunk estimates, the actual sweep
            # still blew the cap).  This is the PROBLEM's shape, not a
            # device fault: reject the whole bin with the structured
            # width status, feed nothing to the admission breaker, and
            # skip bisection — halving a bin cannot un-widen a tree.
            self._dispatch_total.inc(kind="rejected_width")
            for req in reqs:
                self._finish_rejected_width(req, str(exc))
            return
        except Exception as exc:  # noqa: BLE001 — fail/bisect the
            # batch, not the scheduler thread: the service must keep
            # serving.
            self._dispatch_total.inc(kind="failed")
            if len(reqs) == 1:
                logger.warning("serve dispatch failed (isolated "
                               "request %s): %s", reqs[0].id, exc)
                self.admission.record_dispatch(ok=False)
                if retry_depth > 0:
                    # Bisection just isolated the poison request: the
                    # black box should hold the whole bisection walk
                    # and the innocent bin-mates' recovery.
                    flight.trigger(
                        "poison_bin", request=reqs[0].id,
                        trace_id=reqs[0].trace_id,
                        retry_depth=retry_depth, error=str(exc))
                self._finish_error(reqs[0],
                                   f"dispatch failed: {exc}")
                return
            logger.warning(
                "serve dispatch failed (%d requests): bisecting to "
                "isolate the poison request: %s", len(reqs), exc)
            mid = len(reqs) // 2
            for half in (reqs[:mid], reqs[mid:]):
                self.dispatch_retries += 1
                self._retries.inc()
                self._dispatch_attempt(half, retry_depth + 1,
                                       envelope=envelope,
                                       lane_d=lane_d)
            return
        self._complete_batch(reqs, batch_result, values, cycles,
                             t_dev0, t_dev1=None)

    def _complete_batch(self, reqs: List[SolveRequest], batch_result,
                        values, cycles, t_dev0: float,
                        t_dev1: Optional[float] = None) -> None:
        """Decode + terminal tail of a SUCCESSFUL device dispatch,
        shared verbatim by the synchronous path
        (:meth:`_dispatch_attempt_inner`) and the pipelined one
        (:meth:`collect_dispatch`) so their accounting cannot drift:
        per-request decode with its own failure isolation, honest
        ledgers, journal/lifecycle terminals — plus the closed-loop
        feedback taps (pack-model fit samples, speculation hit
        accounting)."""
        self.admission.record_dispatch(ok=True)
        metrics = batch_result.metrics
        self.dispatches += 1
        kind = "batched" if len(reqs) > 1 else "solo"
        self._dispatch_total.inc(kind=kind)
        if len(reqs) > 1:
            self.batched_dispatches += 1
            self.batched_requests += len(reqs)
            self._batched_reqs.inc(len(reqs))
        packing = metrics.get("packing") or "structure"
        if packing in ("envelope", "lane"):
            self.envelope_dispatches += 1
            if packing == "lane":
                self.lane_dispatches += 1
            if len(reqs) > 1:
                self.envelope_packed_requests += len(reqs)
            self._envelope_total.inc(kind=packing)
            self._envelope_waste_g.set(
                metrics.get("envelope_waste") or 0.0)
        self._occupancy.set(
            metrics["n_real"] / metrics["batch_size"])
        pad_lanes = metrics["batch_size"] - metrics["n_real"]
        if pad_lanes:
            self._pad_waste.inc(pad_lanes)
        if t_dev1 is None:
            t_dev1 = time.perf_counter()
        self._feed_closed_loop(reqs, batch_result)
        converged_lanes = metrics.get("converged_lanes") or []
        for i, req in enumerate(reqs):
            # Per-request decode guard: one cost function that raises
            # on its own selected assignment must fail THAT request,
            # not the batch-mates (already solved) or the scheduler
            # thread (which serves everyone after them).
            try:
                assignment = req.meta.assignment_from_indices(
                    values[i])
                cost, violations = req.dcop.solution_cost(assignment)
            except Exception as exc:  # noqa: BLE001
                logger.warning("result decode failed for %s: %s",
                               req.id, exc)
                self._finish_error(req, f"result decode failed: {exc}")
                continue
            # Per-request finish clock AFTER the decode: this
            # request's latency honestly includes its own host
            # post-processing (and its wait behind batch-mates
            # decoded before it — the ledger's ``decode`` column).
            t_done = time.perf_counter()
            ledger = self._request_ledger(
                req, batch_result, t_dev0, t_dev1, t_done)
            req.result = {
                "id": req.id,
                "trace_id": req.trace_id,
                "status": FINISHED,
                "assignment": assignment,
                "cost": cost,
                "violations": violations,
                "cycles": int(cycles[i]),
                "converged": (bool(converged_lanes[i])
                              if i < len(converged_lanes) else None),
                "latency": {
                    "total_s": t_done - req.t_submit,
                    "dispatch_s": batch_result.time_s,
                    "queued_s": (t_done - req.t_submit
                                 - batch_result.time_s),
                },
                "ledger": ledger,
                "batch": {
                    "size": metrics["batch_size"],
                    "n_real": metrics["n_real"],
                    "pad_fraction": metrics["pad_fraction"],
                    "cold_start": metrics["cold_start"],
                    "packing": packing,
                    "envelope_waste": (
                        metrics["envelope_waste_lanes"][i]
                        if i < len(metrics.get(
                            "envelope_waste_lanes") or [])
                        else None),
                },
            }
            if metrics.get("optimal"):
                # Exact-inference dispatch: the served assignment is a
                # certified optimum, and the client can trust it as
                # one (the flag only ever rides a DPOP sweep's
                # result — iterative engines never set it).
                req.result["optimal"] = True
            req.status = FINISHED
            self.completed += 1
            self._req_total.inc(status="ok")
            efficiency.tracker.record_ledger(
                ledger,
                backend=(metrics.get("efficiency") or {}).get(
                    "backend"))
            # The exemplar makes the latency histogram navigable: the
            # bucket this observation lands in remembers this
            # trace_id, so a p99 spike in /metrics is one `pydcop
            # trace query` away from the spans that produced it.
            self._latency.observe(t_done - req.t_submit,
                                  exemplar=req.trace_id)
            self._journal_done(req)
            req.done.set()
            self._publish_lifecycle("finished", req)

    def _feed_closed_loop(self, reqs: List[SolveRequest],
                          batch_result) -> None:
        """The measured-dispatch feedback taps (ISSUE 18): a warm
        maxsum dispatch feeds one (cells, cycles, execute) sample to
        the online pack-model fit, and a cold dispatch whose program
        key was speculatively AOT-built counts as a speculation hit
        (the XLA build left the request path — the cold call resolved
        as a disk-cache hit).  Both are advisory: failures are
        swallowed, the dispatch result is already decided."""
        metrics = batch_result.metrics
        try:
            program_key = metrics.get("program_key")
            if (self._speculator is not None and program_key
                    and metrics.get("cold_start")):
                if self._speculator.record_hit(program_key):
                    self.speculative_hits += 1
            cells = metrics.get("cells_total")
            if cells and not metrics.get("cold_start"):
                from pydcop_tpu.engine import autotune

                if autotune.pack_fit_enabled():
                    run_s = float(metrics.get(
                        "run_time_s", batch_result.time_s))
                    autotune.record_pack_sample(
                        efficiency.backend_name(), int(cells),
                        int(reqs[0].params["max_cycles"]), run_s)
        except Exception:  # noqa: BLE001 — feedback, not serving
            pass

    def _request_ledger(self, req: SolveRequest, batch_result,
                        t_dev0: float, t_dev1: float,
                        t_done: float) -> Dict[str, Any]:
        """One request's time ledger from its contiguous breakpoints:
        submit (admission+compile+journal on the submitting thread),
        queue (bounded queue + coalescing window), plan (flush
        planning), prep (scheduler bookkeeping + host-side batch
        assembly), compile/execute (the device wall, split by the
        overlapping-fields convention), decode (device end → this
        request finished, its own host post-processing included).
        The intervals tile [t_submit, t_done], so the components sum
        to the measured total — the invariant the battery asserts.
        Bisection-retry walls land in ``prep`` (everything between
        dispatch pickup and the SUCCESSFUL device call)."""
        # The inner device wall when the dispatch reported one (the
        # outer time_s additionally holds the profiler's cold-capture
        # and batch-assembly host work — that belongs in ``prep``).
        run_s = float(batch_result.metrics.get(
            "run_time_s", batch_result.time_s))
        compile_s = float(batch_result.compile_time_s)
        split = efficiency.split_device_time(run_s, compile_s)
        t_enq = req.t_enqueue or req.t_submit
        t_disp = req.t_dispatch or t_dev0
        plan_s = min(max(req.plan_s, 0.0), max(t_disp - t_enq, 0.0))
        prep = (max(t_dev0 - t_disp, 0.0)
                + max((t_dev1 - t_dev0) - run_s, 0.0))
        return efficiency.make_ledger(
            t_done - req.t_submit,
            submit=t_enq - req.t_submit,
            queue=max(t_disp - t_enq - plan_s, 0.0),
            plan=plan_s,
            prep=prep,
            compile=split["compile"],
            execute=split["execute"],
            decode=max(t_done - t_dev1, 0.0),
        )

    def run_session_work(self, work) -> None:
        """Scheduler hook: one stateful-session work item (event
        apply / engine segment / close — serving/sessions.py).
        Guarded so a session failure can never kill the scheduler
        thread; session-level error handling lives in the manager."""
        try:
            self.sessions.run_work(work)
        except Exception:  # noqa: BLE001 — last line of defense
            logger.exception("session work crashed")
            done = getattr(work, "done", None)
            if done is not None and not done.is_set():
                work.error = "internal session work error"
                done.set()

    def _check_width(self, dcop: DCOP):
        """Submit-time width gate for ``algo="dpop"``: build the
        pseudo-tree, verdict via engine/dpop.dpop_feasibility (CEC
        shrinkage included — pruning is how the ceiling rises), raise
        :class:`WidthRejected` when even the shrunk hypercubes bust
        ``ops/dpop.MAX_NODE_ELEMENTS``.  Returns the pseudo-tree so
        the dispatch never rebuilds it."""
        from pydcop_tpu.computations_graph import pseudotree as pt
        from pydcop_tpu.engine.dpop import dpop_feasibility

        tree = pt.build_computation_graph(dcop)
        verdict = dpop_feasibility(tree, mode=dcop.objective, cec=True)
        if not verdict["feasible"]:
            effective = (verdict["cec_max_elements"]
                         or verdict["max_elements"])
            raise WidthRejected(
                f"problem too wide for exact inference: largest UTIL "
                f"hypercube has {effective} elements (cap "
                f"{verdict['max_elements_cap']}, induced width "
                f"{verdict['induced_width']}); use the iterative "
                f"solver (algo=maxsum) for this structure",
                max_elements=effective,
                cap=verdict["max_elements_cap"])
        return tree

    def _run_batch_dpop(self, reqs, params):
        """Exact-inference dispatch: one DpopEngine solve per request
        (no cross-problem stacking — the level-batched signature
        buckets batch WITHIN each problem, and same-bin requests share
        every compiled kernel through the signature cache plus the
        service-wide warm set).  Returns the same ``(values, cycles,
        batch_result)`` triple as the stacked path, so the generic
        decode/ledger/lifecycle code downstream is one code path."""
        import numpy as np

        from pydcop_tpu.computations_graph import pseudotree as pt
        from pydcop_tpu.engine.dpop import DpopEngine
        from pydcop_tpu.engine.runner import DeviceRunResult

        t0 = time.perf_counter()
        values, cycles, kernel_calls = [], [], 0
        compile_s = 0.0
        for req in reqs:
            tree = req.exact_tree
            if tree is None:
                tree = pt.build_computation_graph(req.dcop)
            engine = DpopEngine(
                tree, mode=req.dcop.objective, cec=True,
                warm=self._dpop_warm)
            res = engine.run()
            index_of = {
                name: {v: i for i, v in enumerate(dom)}
                for name, dom in zip(req.meta.var_names,
                                     req.meta.domains)
            }
            values.append(np.asarray(
                [index_of[n][res.assignment[n]]
                 for n in req.meta.var_names], dtype=np.int64))
            cycles.append(res.cycles)
            kernel_calls += res.metrics.get("kernel_calls", 0)
            compile_s += res.compile_time_s
        elapsed = time.perf_counter() - t0
        with self._lock:
            self.dpop_dispatches += 1
        batch_result = DeviceRunResult(
            assignment={},
            cycles=max(cycles) if cycles else 0,
            converged=True,
            time_s=elapsed,
            compile_time_s=min(compile_s, elapsed),
            metrics={
                "batch_size": len(reqs),
                "n_real": len(reqs),
                "pad_fraction": 0.0,
                "cold_start": compile_s > 0.0,
                "run_time_s": elapsed,
                "converged_lanes": [True] * len(reqs),
                "packing": "dpop",
                "optimal": True,
                "kernel_calls": kernel_calls,
            },
        )
        if efficiency.tracker.enabled:
            record = efficiency.tracker.record_dispatch(
                key=f"dpop_batch_{len(reqs)}",
                structure=efficiency.structure_label(reqs[0].graph),
                backend=efficiency.backend_name(),
                time_s=elapsed, compile_s=batch_result.compile_time_s,
                cycles=max(cycles) if cycles else 0,
                n_real=len(reqs), batch_size=len(reqs),
                pad_fraction=0.0, envelope_waste=0.0,
                packing="dpop", cost_entry=None,
            )
            if record is not None:
                batch_result.metrics["efficiency"] = record
        return np.asarray(values), np.asarray(cycles), batch_result

    def _run_batch(self, reqs, params, envelope=None,
                   lane_d: Optional[int] = None):
        """The device call, isolated for tests to stub failures.
        ``envelope`` routes a heterogeneous group through mask-padded
        envelope stacking, ``lane_d`` through the disjoint-union lane
        pack; both default to the exact same-structure stack."""
        if params.get("algo") == "dpop":
            return self._run_batch_dpop(reqs, params)
        graphs = [r.graph for r in reqs]
        if lane_d is not None:
            return engine_batch.run_lane_packed(
                graphs,
                max_cycles=params["max_cycles"],
                damping=params["damping"],
                damping_nodes=params["damping_nodes"],
                stability=params["stability"],
                d_env=lane_d,
                # Coarse union rounding: a handful of compiled
                # programs must cover every group composition (see
                # binning.UNION_LADDER).
                ladder=binning.UNION_LADDER,
            )
        return engine_batch.run_stacked(
            graphs,
            max_cycles=params["max_cycles"],
            damping=params["damping"],
            damping_nodes=params["damping_nodes"],
            stability=params["stability"],
            pad_to_bins=self.bin_sizes,
            prune=bool(params.get("prune", 0)),
            envelope=envelope,
        )

    def _finish_rejected_width(self, req: SolveRequest, message: str):
        """Terminal for a dispatch-time width bust: an ERROR result
        whose ``status_detail`` is ``rejected_width`` (the front end
        maps it to a 400 — the client sent an un-servable problem
        shape, not a flaky one worth retrying)."""
        req.result = {
            "id": req.id, "trace_id": req.trace_id,
            "status": ERROR,
            "status_detail": "rejected_width",
            "error": f"problem too wide for exact inference: {message}",
            "latency": {
                "total_s": time.perf_counter() - req.t_submit,
            },
            "ledger": self._terminal_ledger(req),
        }
        req.status = ERROR
        self.failed += 1
        self._req_total.inc(status="rejected_width")
        self._journal_done(req)
        req.done.set()
        self._publish_lifecycle("error", req)

    def _finish_error(self, req: SolveRequest, message: str):
        req.result = {
            "id": req.id, "trace_id": req.trace_id,
            "status": ERROR, "error": message,
            "latency": {
                "total_s": time.perf_counter() - req.t_submit,
            },
            "ledger": self._terminal_ledger(req),
        }
        req.status = ERROR
        self.failed += 1
        self._req_total.inc(status="error")
        self._journal_done(req)
        req.done.set()
        self._publish_lifecycle("error", req)

    def _finish_expired(self, req: SolveRequest):
        """Terminal EXPIRED: the deadline passed before dispatch.  A
        504 on the wire, ``rejected_deadline`` in the ledger, and a
        journaled terminal — an expired request must not resurrect on
        a --recover restart."""
        req.result = {
            "id": req.id, "trace_id": req.trace_id,
            "status": EXPIRED,
            "error": (f"deadline of {req.deadline_s}s exceeded "
                      "before dispatch"),
            "latency": {
                "total_s": time.perf_counter() - req.t_submit,
            },
            "ledger": self._terminal_ledger(req),
        }
        req.status = EXPIRED
        self.expired += 1
        self._req_total.inc(status="rejected_deadline")
        self._journal_done(req)
        req.done.set()
        self._publish_lifecycle("expired", req)

    def _terminal_ledger(self, req: SolveRequest) -> Dict[str, Any]:
        """Ledger for a request that terminated without a decoded
        result (error/expired), still summing to the measured total.
        Time after dispatch pickup — failed device attempts, decode
        failures — is ``prep``, not queue: an operator chasing a
        queue-wait spike must not be sent device-side seconds."""
        now = time.perf_counter()
        t_enq = req.t_enqueue or req.t_submit
        t_disp = req.t_dispatch or now
        return efficiency.make_ledger(
            now - req.t_submit,
            submit=t_enq - req.t_submit,
            queue=max(min(t_disp, now) - t_enq, 0.0),
            prep=max(now - t_disp, 0.0) if req.t_dispatch else 0.0,
        )

    def _publish_lifecycle(self, phase: str, req: SolveRequest):
        """One request-lifecycle event onto the SSE ``/events``
        stream (accepted → dispatched → finished / error / expired,
        each carrying the trace_id) and, when tracing/flight is on, a
        matching trace instant — a watching client follows a request
        through the service in real time with the same id it would
        hand to ``pydcop trace query``."""
        if tracer.active:
            tracer.instant(f"serve_{phase}", "serving",
                           request=req.id, trace_id=req.trace_id)
        CycleSnapshotter.publish({
            "ts": time.time(),
            "event": "request",
            "phase": phase,
            "id": req.id,
            "trace_id": req.trace_id,
            "status": req.status,
        })

    def expire_if_overdue(self, req: SolveRequest) -> bool:
        """Scheduler hook: drop already-expired work BEFORE binning.
        True means the request was expired and must not be
        dispatched."""
        if req.deadline_s is None:
            return False
        if time.perf_counter() - req.t_submit <= req.deadline_s:
            return False
        self._finish_expired(req)
        return True

    def _journal_done(self, req: SolveRequest):
        """Journal a terminal outcome WITH the result payload: the
        outcome is durable, not just the fact of completion, so a
        client polling across a crash gets its 200 from the
        replacement process (journal.completed_results).  Never
        raises into the scheduler thread: a failed completion append
        costs at most one duplicate solve after a crash, never the
        service."""
        if self._journal is None:
            return
        try:
            try:
                rec = journal_mod.completed_record(
                    req.id, req.status, result=req.result)
                journal_mod.encode_record(rec)
            except (TypeError, ValueError):
                # A result that will not serialize (should not
                # happen — it is served as JSON) degrades to the
                # payload-less tombstone rather than losing the
                # terminal record entirely.
                rec = journal_mod.completed_record(req.id, req.status)
            self._journal.append(rec)
            self._journal_records.inc(kind="completed")
        except Exception as exc:  # noqa: BLE001
            logger.warning("journal completion append failed for "
                           "%s: %s", req.id, exc)

    # -- introspection ------------------------------------------------- #

    def journal_summary(self) -> Dict[str, Any]:
        """Journal backlog, the operator's replay-debt gauge:
        ``pending_replayable`` (accepted records with no terminal —
        exactly what a ``--recover`` restart would replay right now)
        and the journal's on-disk byte size.  Surfaced in /healthz
        while a journaled service runs, and folded into postmortem
        bundles (observability/flight.py's journal provider)."""
        with self._lock:
            pending = sum(1 for r in self._requests.values()
                          if not r.done.is_set())
        size = 0
        if self._journal is not None:
            try:
                size = os.path.getsize(self._journal.path)
            except OSError:
                size = 0
        return {
            "dir": self.journal_dir,
            "active": self._journal is not None,
            "pending_replayable": pending,
            # Open sessions are replay debt too: a --recover restart
            # rebuilds each one from its open/ckpt/event records.
            "open_sessions": self.sessions.active_count(),
            "journal_bytes": size,
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            tracked = len(self._requests)
            recent_decisions = list(self.envelope_decisions)[-8:]
        eff = efficiency.tracker.summary()
        return {
            "queue_depth": self._queue.qsize(),
            "high_water": self.admission.policy.high_water,
            "breaker_state": self.admission.breaker_state,
            "dispatches": self.dispatches,
            "batched_dispatches": self.batched_dispatches,
            "batched_requests": self.batched_requests,
            "envelope_packing": self.envelope_packing,
            "envelope_dispatches": self.envelope_dispatches,
            "lane_dispatches": self.lane_dispatches,
            "envelope_packed_requests": self.envelope_packed_requests,
            "envelope_decisions": recent_decisions,
            "completed": self.completed,
            "failed": self.failed,
            "expired": self.expired,
            "replayed": self.replayed,
            "dispatch_retries": self.dispatch_retries,
            "dpop_dispatches": self.dpop_dispatches,
            "portfolio_resolved": self.portfolio_resolved,
            "deduped": self.deduped,
            # The closed-loop hot path's /stats faces (ISSUE 18):
            # pipelined launch/collect counters with the overlap
            # fraction, and the speculative compiler's ledger —
            # ``speculative_compiles_total`` with at least one hit is
            # the smoke-asserted signal that compile stalls left the
            # request path.
            "pipeline": {
                "enabled": self.pipeline,
                "pipelined_dispatches": self.pipelined_dispatches,
                "overlap_fraction":
                    eff["pipeline_overlap_fraction"],
            },
            "speculation": dict(
                {"enabled": self.speculate,
                 "hits": self.speculative_hits},
                **(self._speculator.stats()
                   if self._speculator is not None else
                   {"speculative_compiles_total": 0})),
            "journal": (self.journal_dir
                        if self._journal is not None else None),
            "sessions": self.sessions.stats(),
            "tracked_requests": tracked,
            "max_batch": self.max_batch,
            "batch_window_s": self.batch_window_s,
            "bin_sizes": list(self.bin_sizes),
            # The /stats face of the histogram exemplars: the p50/p99
            # buckets' last-seen trace_ids, each resolvable by
            # `pydcop trace query --request <trace_id>`.
            "latency_exemplars": {
                q: self._latency.quantile_exemplar(v)
                for q, v in (("p50", 0.50), ("p99", 0.99))
            },
            # The efficiency plane's compact face (ISSUE 14): resolved
            # backend, attainment/useful-work rollup and the ledger's
            # where-the-time-went component sums.  The full document
            # (per-structure top-N, waste taxonomy) lives on
            # ``GET /profile``.
            "efficiency": eff,
        }

    def health_summary(self) -> Dict[str, Any]:
        """The /healthz contribution: breaker open → failing (503);
        journaled services also report their replay debt
        (``journal.pending_replayable`` / ``journal_bytes``) so an
        operator sees what a restart would replay BEFORE restarting."""
        stats = self.stats()
        status = ("failing" if stats["breaker_state"] == "open"
                  else "ok")
        summary = {"status": status, "serving": stats}
        if self._journal is not None:
            summary["journal"] = self.journal_summary()
        return summary


class QueueFullRace(AdmissionRejected):
    """put_nowait lost the depth race: treated exactly like a
    high-water rejection (429)."""

    http_status = 429

"""High-level solve API.

Reference parity: pydcop/infrastructure/run.py:52 ``solve()`` — build
graph → distribute → run → return assignment.  Here the default backend
is the device engine (one jitted BSP program); ``backend="thread"`` runs
the agent-mode runtime for reference-equivalent distributed execution.
"""

import os
import time
from typing import Any, Dict, Optional, Union

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.dcop.dcop import DCOP


class SolveResult(dict):
    """Dict-like result: assignment, cost, violations, cycles, times."""

    @property
    def assignment(self) -> Dict[str, Any]:
        return self["assignment"]

    @property
    def cost(self) -> float:
        return self["cost"]


def solve(dcop: DCOP, algo_def: Union[str, AlgorithmDef],
          distribution: str = "oneagent",
          backend: str = "device",
          timeout: Optional[float] = None,
          max_cycles: int = 1000,
          algo_params: Optional[Dict[str, Any]] = None,
          mesh=None, n_devices: Optional[int] = None,
          shards: Optional[int] = None,
          warmup: bool = False,
          ui_port: Optional[int] = None,
          collector=None,
          collect_moment: str = "value_change",
          collect_period: float = 1.0,
          delay: Optional[float] = None,
          checkpoint_dir: Optional[str] = None,
          checkpoint_every: Optional[int] = None,
          checkpoint_async: bool = True,
          checkpoint_keep: int = 2,
          resume: bool = False,
          fault_plan=None,
          recovery=None,
          health=None,
          trace: Optional[str] = None,
          trace_format: str = "chrome",
          metrics_file: Optional[str] = None,
          metrics_every: Optional[int] = None,
          serve_metrics: Optional[int] = None,
          ) -> SolveResult:
    """Solve a DCOP and return assignment + quality metrics.

    backend="device": batched engine on TPU/CPU devices (default).
    backend="thread": agent-mode runtime (threads + in-process messages),
    reference-equivalent semantics.

    ``algo_def="auto"`` (device backend) races the whole-algorithm
    portfolio on the compiled graph — maxsum with/without
    branch-and-bound pruning and decimation, plus the vectorized
    local-search kernels (dsa/mgm/gdba) — toward the best cost
    reachable in a short budget, solves with the winner, and caches
    the decision by structure signature
    (engine/autotune.autotune_portfolio): a second same-structure
    solve replays the choice with zero measurement.  The decision and
    per-candidate timings land in ``metrics['portfolio']``.

    Scaling knobs (docs/sharding.md): ``n_devices`` row-shards factor
    buckets over a mesh with replicated variable tables (any device
    algorithm; per-superstep all-reduce is O(V·D)); ``shards=N``
    runs the PARTITIONED engine instead (maxsum family) — a
    min-edge-cut partition assigns variables and factors to shards
    and only cut-edge halo state is exchanged per superstep
    (O(cut·D)).  Partition statistics (``edge_cut_fraction``,
    ``halo_vars_per_shard``, ``balance``) and communication
    accounting come back in ``metrics``.  The two knobs are mutually
    exclusive.

    Resilience knobs (docs/resilience.md): ``checkpoint_dir`` chunks a
    device-mode solve into ``checkpoint_every``-cycle segments with an
    NPZ state snapshot between segments; ``resume=True`` continues
    from the newest snapshot in that directory instead of cycle 0
    (identical final result — the battery asserts it).
    ``checkpoint_async`` (default True) moves each snapshot's
    device→host copy + file write onto a background writer thread so
    it overlaps the next segment's device compute instead of
    serializing with it (all snapshots are flushed before the solve
    returns); ``checkpoint_async=False`` restores the synchronous
    write between segments.  ``checkpoint_keep`` bounds the retention
    (keep-last-N snapshots, default 2; the newest valid one is never
    pruned).  ``fault_plan`` (a resilience.faults.FaultPlan) runs the
    thread backend under seeded message faults and crash injection.

    Self-healing knobs (docs/resilience.md "Failure detection &
    recovery"): ``recovery`` (a resilience.recovery.RecoveryPolicy)
    arms segment-boundary guards on a device solve — NaN/Inf scan +
    optional cost-divergence window, rollback to the last valid
    snapshot with escalating intervention, ``RecoveryExhausted``
    carrying the partial trajectory once the restart budget is spent;
    guard trip/attempt counts come back in ``metrics``.  ``health``
    (a resilience.health.HealthConfig) runs the thread backend under
    active heartbeat failure detection — phi-accrual suspicion,
    bounded ``agent_dead`` verdicts feeding the repair path — and
    returns the verdict history under the result's ``health`` key.

    Observability knobs (docs/observability.md): ``trace`` records
    the whole solve on the process tracer and writes a Chrome
    ``trace_event`` JSON (``trace_format="chrome"``, open in
    chrome://tracing / Perfetto) or line-delimited JSON
    (``"jsonl"``) to that path.  ``metrics_file`` activates the
    metrics registry, appends JSONL snapshots — in device mode one per
    ``metrics_every``-cycle engine chunk (honest per-chunk timings +
    a cost-vs-cycle curve, returned in ``metrics['cost_curve']``),
    in thread mode one each time the global cycle advances by
    ``metrics_every`` — and writes a Prometheus text dump to
    ``<metrics_file>.prom`` when the solve ends.  ``serve_metrics``
    (a port; 0 = OS-assigned) serves live telemetry over HTTP for the
    duration of the solve — ``/metrics`` (Prometheus text),
    ``/healthz`` (health verdicts) and ``/events`` (SSE cycle/cost
    stream) — so a long run is scrapeable while it runs
    (observability/server.py).  An observed device solve also records
    XLA cost attribution: measured flops/bytes/peak memory per
    compiled segment land in ``metrics['xla_cost']`` keyed by jit
    cache key (explicit ``available: False`` markers on backends that
    return nothing).  All default off and cost nothing while off.
    Interactions: with ``checkpoint_dir`` the
    chunking follows ``checkpoint_every``, so snapshots land every
    ``max(checkpoint_every, metrics_every)`` cycles; ``warmup=True``
    keeps the plain (unsegmented) device path — the solve is still
    traced, but without per-chunk points or a cost curve.

    warmup=True runs the compiled program once untimed before the timed
    call, so one-shot solves report steady-state rates instead of
    compile-dominated ones (device backend only).  The warm-up run is a
    FULL discarded solve (the cycle count is baked into the compiled
    program, so a shorter variant would compile a different
    executable): expect ~2x wall time for large max_cycles, and prefer
    warmup=False when only the answer matters.  Host-driven sweep
    algorithms (dpop, syncbb, ncbb) and maxsum decimation ignore it —
    their runners already report compile time separately.

    Example::

        >>> from pydcop_tpu.dcop.dcop import DCOP
        >>> from pydcop_tpu.dcop.objects import Domain, Variable
        >>> from pydcop_tpu.dcop.relations import constraint_from_str
        >>> d = Domain('d', '', [0, 1])
        >>> x, y = Variable('x', d), Variable('y', d)
        >>> dcop = DCOP('doc', objective='min')
        >>> dcop.add_constraint(
        ...     constraint_from_str('c', '(x + y - 1)**2', [x, y]))
        >>> res = solve(dcop, 'dpop')
        >>> res['status'], round(res['cost'], 3)
        ('FINISHED', 0.0)
    """
    portfolio_info = None
    if isinstance(algo_def, str) and algo_def == "auto":
        if backend != "device":
            raise ValueError(
                "algo='auto' races device kernels: use "
                "backend='device'")
        algo_def, portfolio_info = _resolve_auto_algo(
            dcop, algo_params or {})
    if isinstance(algo_def, str):
        algo_def = AlgorithmDef.build_with_default_param(
            algo_def, algo_params or {}, mode=dcop.objective
        )
    module = load_algorithm_module(algo_def.algo)

    # Resilience knobs are backend-specific: reject silently-ignored
    # combinations instead of letting a chaos test believe faults were
    # injected (or a preemptible run believe it checkpointed).
    if fault_plan is not None and backend == "device":
        raise ValueError(
            "fault_plan wraps agent transports: use backend='thread'"
        )
    if (checkpoint_dir is not None or resume) and backend != "device":
        raise ValueError(
            "checkpointing segments the device engine's solve loop: "
            "use backend='device'"
        )
    if resume and checkpoint_dir is None:
        raise ValueError(
            "resume=True needs checkpoint_dir: there is no snapshot "
            "location to resume from"
        )
    if recovery is not None and backend != "device":
        raise ValueError(
            "recovery guards the device engine's segmented loop: "
            "use backend='device'"
        )
    if health is not None and backend != "thread":
        raise ValueError(
            "health monitoring instruments agent threads: use "
            "backend='thread'"
        )
    if shards is not None and shards > 1:
        if backend != "device":
            raise ValueError(
                "shards= partitions the device engine's factor "
                "graph: use backend='device'"
            )
        if not getattr(module, "SUPPORTS_SHARDS", False):
            raise NotImplementedError(
                f"Algorithm {algo_def.algo} has no partitioned "
                "engine (maxsum family only); use n_devices= for "
                "replicated-variable sharding"
            )

    session = None
    if (trace is not None or metrics_file is not None
            or serve_metrics is not None):
        from pydcop_tpu.observability import ObservabilitySession

        session = ObservabilitySession(
            trace, trace_format, metrics_file,
            serve_port=serve_metrics,
        ).start()
    try:
        from pydcop_tpu.observability.trace import tracer

        with tracer.span("solve", "api", algo=algo_def.algo,
                         backend=backend, max_cycles=max_cycles):
            result = _solve(
                dcop, algo_def, module, distribution=distribution,
                backend=backend, timeout=timeout,
                max_cycles=max_cycles, mesh=mesh, n_devices=n_devices,
                shards=shards,
                warmup=warmup, ui_port=ui_port, collector=collector,
                collect_moment=collect_moment,
                collect_period=collect_period, delay=delay,
                checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                checkpoint_async=checkpoint_async,
                checkpoint_keep=checkpoint_keep, resume=resume,
                fault_plan=fault_plan, recovery=recovery,
                health=health, observing=session is not None,
                metrics_file=metrics_file, metrics_every=metrics_every,
                serving=serve_metrics is not None,
            )
            if portfolio_info is not None:
                result.setdefault("metrics", {})[
                    "portfolio"] = portfolio_info
            return result
    finally:
        if session is not None:
            session.finish()


def _resolve_auto_algo(dcop: DCOP, algo_params: Dict[str, Any]):
    """Resolve ``algo="auto"`` through the portfolio racer: replay a
    persisted same-structure decision when one exists (no re-race —
    asserted in the work-reduction battery), otherwise compile once
    and race the candidates on the real graph.  Returns
    ``(AlgorithmDef, info)`` with the winner's extra params merged
    over the caller's."""
    from pydcop_tpu.engine.autotune import (
        PORTFOLIO_PARAMS,
        autotune_portfolio,
        cached_portfolio_choice,
        dcop_portfolio_key,
        dpop_portfolio_runner,
    )

    key = dcop_portfolio_key(dcop)
    choice = cached_portfolio_choice(key)
    if choice is not None:
        info = {"algo": choice, "portfolio_source": "cache",
                "portfolio_key": key}
    else:
        from pydcop_tpu.engine.compile import compile_dcop

        graph, meta = compile_dcop(
            dcop, noise_level=float(
                algo_params.get("noise", 0.01) or 0.0))
        # Exact inference enters the race width-keyed: the runner is
        # None past DPOP_RACE_MAX_ELEMENTS (computed from the
        # pseudo-tree, CEC shrinkage included), so wide structures
        # resolve to an iterative winner without paying an exact
        # attempt.
        info = autotune_portfolio(
            graph, key=key, meta=meta,
            extra_runners={
                "dpop": dpop_portfolio_runner(dcop, graph, meta)})
    algo, extra = PORTFOLIO_PARAMS[info["algo"]]
    module = load_algorithm_module(algo)
    allowed = {p.name for p in module.algo_params}
    params = {k: v for k, v in algo_params.items() if k in allowed}
    dropped = sorted(set(algo_params) - set(params))
    if dropped:
        # The caller parameterized for one family; the race picked
        # another.  Dropping (loudly) beats failing the solve — the
        # caller asked for "whatever wins".
        import logging

        logging.getLogger("pydcop.api").warning(
            "algo='auto' winner %s does not take parameter(s) %s; "
            "ignored", algo, ", ".join(dropped))
    params.update(extra)
    return AlgorithmDef.build_with_default_param(
        algo, params, mode=dcop.objective), info


class ServeHandle:
    """A running solve service + HTTP front end.

    ``url``/``port`` locate the front end; ``service`` is the
    underlying :class:`~pydcop_tpu.serving.service.SolveService`
    (submit/result work in-process too); ``stop()`` drains the queue
    and shuts both down.  Context-manager friendly."""

    def __init__(self, service, front_end):
        self.service = service
        self.front_end = front_end

    @property
    def url(self):
        return self.front_end.url

    @property
    def port(self):
        return self.front_end.port

    def stop(self, drain: bool = True) -> Dict[str, Any]:
        """Stop front end + service; returns the service's drain
        summary (``drained`` / ``replayable`` / ``failed_pending``)."""
        self.front_end.stop()
        return self.service.stop(drain=drain)

    def __enter__(self) -> "ServeHandle":
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class FleetHandle:
    """A running fleet: N serve-worker processes behind one router
    front end (docs/serving.md "Fleet-scale serving").  ``router`` is
    the :class:`~pydcop_tpu.serving.router.FleetRouter` (replica
    states, routing stats); ``stop()`` SIGTERM-drains every worker
    and shuts the front end down."""

    def __init__(self, router, front_end):
        self.router = router
        self.front_end = front_end

    @property
    def url(self):
        return self.front_end.url

    @property
    def port(self):
        return self.front_end.port

    def stop(self, drain: bool = True) -> Dict[str, Any]:
        self.front_end.stop()
        return self.router.stop(drain=drain)

    def __enter__(self) -> "FleetHandle":
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def _write_port_file(path: str, port: int) -> None:
    """Atomically publish the bound port (the fleet router's worker
    handshake; also handy for scripts wrapping ``--port 0``)."""
    import tempfile

    directory = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".port_")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(f"{port}\n")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def serve(port: int = 8080, host: str = "127.0.0.1",
          max_queue: int = 256, batch_window_s: float = 0.02,
          max_batch: int = 16, high_water: Optional[int] = None,
          default_params: Optional[Dict[str, Any]] = None,
          breaker_failures: int = 3, breaker_reset_s: float = 5.0,
          result_keep: int = 4096,
          journal_dir: Optional[str] = None,
          journal_sync: bool = False,
          recover: bool = False,
          envelope_packing: bool = True,
          envelope_overhead_ms: Optional[float] = None,
          pipeline: bool = True,
          speculate: bool = True,
          session_max: int = 64,
          session_segment_cycles: Optional[int] = None,
          session_checkpoint_every_events: int = 8,
          session_certify_after: Optional[float] = None,
          replicas: int = 1,
          affinity: str = "structure",
          compile_cache_dir: Optional[str] = None,
          heartbeat_s: float = 0.25,
          probe_timeout_s: Optional[float] = None,
          spill_slack: int = 4,
          hosts: int = 1,
          slo_p99_ms: Optional[float] = None,
          min_replicas: Optional[int] = None,
          max_replicas: Optional[int] = None,
          join: Optional[str] = None,
          host_id: Optional[str] = None,
          fleet_trace: Optional[bool] = None,
          port_file: Optional[str] = None,
          block: bool = False) -> Optional[Any]:
    """Start the multi-tenant solve service (docs/serving.md).

    Incoming problems are binned by structure signature and
    same-structure requests are stacked into ONE vmapped device
    dispatch (the batched-BP throughput lever); results stream back
    per request with latency accounting and a time LEDGER whose
    components sum to the measured total
    (docs/observability.md "Efficiency accounting").  The front end
    serves ``POST /solve`` / ``GET /result/<id>`` / ``GET /stats``
    plus the live telemetry routes (``/metrics``, ``/healthz``,
    ``/events``, ``/profile`` — the backend-honest efficiency
    rollup ``pydcop profile report --url`` renders).

    Different-structure requests that structure binning would
    dispatch solo are additionally packed into shape-envelope
    dispatches when a per-flush cost model says the padded batch
    beats solo dispatches (``envelope_packing``, on by default —
    results stay bit-identical to solo solves;
    ``envelope_overhead_ms`` tunes the modeled per-dispatch fixed
    cost the decision weighs against padding waste — docs/serving.md
    "Envelope batching").

    Admission control: a submit past the queue's ``high_water``
    (default ``max_queue``) is rejected with 429; repeated dispatch
    failure opens a circuit breaker (``breaker_failures`` failures,
    ``breaker_reset_s`` probe delay) that turns submits 503 and
    ``/healthz`` failing.

    ``journal_dir`` enables the durable request journal (every 202 is
    crash-durable); ``recover=True`` replays accepted-but-unfinished
    journal entries through the queue on startup (``pydcop serve
    --journal_dir D --recover``); ``journal_sync`` fsyncs per record.

    Stateful sessions (docs/sessions.md): ``POST /session`` opens a
    long-lived dynamic-DCOP solve, ``PATCH /session/<id>/events``
    streams scenario events applied between engine segments without
    recompiling when the shape survives, SSE streams anytime results
    and the journal replays whole sessions after a crash.
    ``session_max`` bounds live sessions (each keeps a warm engine),
    ``session_segment_cycles`` overrides the default anytime-segment
    granularity, ``session_checkpoint_every_events`` the engine-state
    snapshot cadence (journaled services; smaller = faster recovery,
    more snapshot writes).  ``session_certify_after=S`` arms the
    exact-inference oracle tier (docs/sessions.md "The oracle tier"):
    a session whose event stream has quiesced for S seconds gets a
    background DPOP solve of its current problem that either
    certifies the warm fixpoint as optimal or upgrades the served
    assignment to the true optimum, publishing the certified-cost
    delta on the session SSE stream and in ``/stats``.

    Fleet scaling (docs/serving.md "Fleet-scale serving"):
    ``replicas=N`` (N > 1) spawns N ``pydcop serve`` WORKER PROCESSES
    — each a full solve service with its own scheduler thread,
    journal segment (``<journal_dir>/replica-<k>/``) and /metrics —
    behind a structure-affinity router speaking this same wire
    protocol; the return value is a :class:`FleetHandle`.
    ``affinity`` picks the routing policy (``"structure"``:
    rendezvous-hash on the admission-time structure key so
    same-structure traffic lands where the compiled program is warm;
    ``"round_robin"``: the A/B baseline), ``heartbeat_s`` /
    ``spill_slack`` tune replica death detection and hot-spot
    spillover.  ``compile_cache_dir`` enables the persistent AOT
    compile cache (engine/aotcache.py) — workers (and the
    single-service path) enable it BEFORE their first jit, so a fresh
    replica serves its first same-structure request without paying
    XLA compilation.

    Elastic fleet (docs/serving.md "Elastic fleet"): ``hosts=H``
    stripes locally spawned replicas over H simulated host identities
    (host-kill chaos, CI two-host topologies); ``slo_p99_ms`` +
    ``max_replicas`` arm SLO-driven autoscaling (the router grows the
    fleet toward ``max_replicas`` when rolling p99 or queue depth
    breaches the SLO, drains back toward ``min_replicas`` — migrating
    warm sessions off, never killing them — when quiet).  ``join``
    turns a SINGLE-replica serve into a remote fleet member: after
    the front end binds, the worker announces its own URL to the
    router at ``join`` via ``POST /fleet/join`` (``host_id``
    overrides the announced host identity, default
    :func:`pydcop_tpu.engine.multihost.fleet_host_id`); incompatible
    with ``replicas > 1``.

    ``port=0`` asks the OS for a free port (``port_file`` atomically
    publishes the assignment — the fleet worker handshake).
    ``block=True`` (the ``pydcop serve`` CLI) serves until
    SIGTERM/SIGINT, then STOPS WITH DRAIN — an orchestrated restart
    (k8s-style) never drops accepted work: queued requests either
    finish in the drain window or stay journaled-replayable, and the
    drained count is logged on exit.  Returns None.  ``block=False``
    returns a :class:`ServeHandle` / :class:`FleetHandle` (both
    context managers) for embedding and tests.

    ``fleet_trace`` forces fleet-wide causal tracing on/off
    (docs/observability.md "Fleet tracing"): the router mints one
    trace context per admission, stamps it on every forwarded
    submit/event-batch/fence/migration/retry, and collects replica
    spans for ``GET /fleet/forensics/<id>``.  ``None`` (default)
    defers to ``PYDCOP_FLEET_TRACE`` (on unless set to 0); an
    explicit value is exported to that env var so spawned workers
    inherit it.
    """
    if fleet_trace is not None:
        # The knob lives in the environment on purpose: spawned fleet
        # workers inherit it, and every header/shipping decision
        # reads it per call — so toggling is honest fleet-wide.
        os.environ["PYDCOP_FLEET_TRACE"] = "1" if fleet_trace else "0"
    if join and replicas > 1:
        raise ValueError(
            "join= is for single-replica remote workers; a local "
            "fleet (replicas > 1) IS the router — point the workers' "
            "join at its URL instead")
    if replicas > 1:
        return _serve_fleet(
            port=port, host=host, max_queue=max_queue,
            batch_window_s=batch_window_s, max_batch=max_batch,
            high_water=high_water, default_params=default_params,
            breaker_failures=breaker_failures,
            breaker_reset_s=breaker_reset_s, result_keep=result_keep,
            journal_dir=journal_dir, journal_sync=journal_sync,
            envelope_packing=envelope_packing,
            envelope_overhead_ms=envelope_overhead_ms,
            pipeline=pipeline, speculate=speculate,
            session_max=session_max,
            session_segment_cycles=session_segment_cycles,
            session_checkpoint_every_events=(
                session_checkpoint_every_events),
            session_certify_after=session_certify_after,
            replicas=replicas, affinity=affinity,
            compile_cache_dir=compile_cache_dir,
            heartbeat_s=heartbeat_s,
            probe_timeout_s=probe_timeout_s,
            spill_slack=spill_slack,
            hosts=hosts, slo_p99_ms=slo_p99_ms,
            min_replicas=min_replicas, max_replicas=max_replicas,
            port_file=port_file, block=block)
    if compile_cache_dir:
        # Before the service compiles anything: the cache-dir config
        # silently no-ops once a jit has run (engine/aotcache latch).
        from pydcop_tpu.engine.aotcache import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache(compile_cache_dir)
    from pydcop_tpu.serving.admission import AdmissionPolicy
    from pydcop_tpu.serving.http import ServeFrontEnd
    from pydcop_tpu.serving.service import SolveService

    service = SolveService(
        max_queue=max_queue,
        batch_window_s=batch_window_s,
        max_batch=max_batch,
        default_params=default_params,
        admission=AdmissionPolicy(
            high_water=(high_water if high_water is not None
                        else max_queue),
            breaker_failures=breaker_failures,
            breaker_reset_s=breaker_reset_s,
        ),
        result_keep=result_keep,
        journal_dir=journal_dir,
        journal_sync=journal_sync,
        recover=recover,
        envelope_packing=envelope_packing,
        envelope_overhead_ms=envelope_overhead_ms,
        pipeline=pipeline,
        speculate=speculate,
        session_max=session_max,
        session_segment_cycles=session_segment_cycles,
        session_checkpoint_every_events=(
            session_checkpoint_every_events),
        session_certify_after=session_certify_after,
    ).start()
    try:
        front_end = ServeFrontEnd(service, port=port, host=host).start()
    except Exception:
        service.stop(drain=False)
        raise
    handle = ServeHandle(service, front_end)
    import sys

    print(f"pydcop serve: listening on {handle.url} "
          "(POST /solve, GET /result/<id>, /metrics, /healthz)",
          file=sys.stderr)
    if port_file:
        _write_port_file(port_file, handle.port)
    if join:
        # Announce AFTER the front end binds: the router health-probes
        # the announced URL before admitting it to the fleet.
        _announce_join(join, handle.url, host_id,
                       journal_dir=journal_dir)
    if not block:
        return handle
    _serve_until_signal(
        handle,
        lambda summary: (
            "pydcop serve: shut down — "
            f"{summary['drained']} request(s) drained, "
            f"{summary['replayable']} journaled replayable, "
            f"{summary['failed_pending']} failed pending"))
    return None


def _announce_join(join_url: str, own_url: str,
                   host_id: Optional[str] = None,
                   journal_dir: Optional[str] = None) -> bool:
    """Announce this worker to a fleet router's ``POST /fleet/join``.

    Best-effort with small retries (the router may still be binding
    during a parallel bring-up): a failed announce leaves the worker
    serving standalone with a warning — operators re-announce by
    restarting or curling /fleet/join themselves — rather than
    refusing to serve at all.

    ``journal_dir`` rides along when the worker journals: a router
    that can see the same filesystem uses it for dead-session
    adoption (serving/migration.adopt_dead_sessions).  The socket I/O
    routes through the netfault seam like every other fleet link, so
    an injected partition also severs discovery."""
    import json
    import sys
    import time
    import urllib.parse

    from pydcop_tpu.engine.multihost import fleet_host_id
    from pydcop_tpu.serving import netfault

    own_host_id = host_id or fleet_host_id()
    doc = {"url": own_url, "host_id": own_host_id}
    if journal_dir:
        doc["journal_dir"] = journal_dir
    payload = json.dumps(doc).encode()
    parsed = urllib.parse.urlsplit(join_url)
    router_host = parsed.hostname or "127.0.0.1"
    router_port = parsed.port or 80
    path = (parsed.path.rstrip("/") or "") + "/fleet/join"
    last: Optional[Exception] = None
    for attempt in range(5):
        if attempt:
            time.sleep(min(0.5 * attempt, 2.0))
        try:
            status, _ctype, body = netfault.exchange(
                ("worker", own_host_id), ("router", router_host),
                router_host, router_port, "POST", path,
                body=payload, timeout=5.0)
            if status >= 400:
                raise ValueError(
                    f"join answered {status}: {body[:200]!r}")
            print(f"pydcop serve: joined fleet at {join_url}",
                  file=sys.stderr)
            return True
        except (OSError, ValueError) as exc:
            last = exc
    print(f"pydcop serve: fleet join at {join_url} failed ({last}); "
          "serving standalone", file=sys.stderr)
    return False


def _serve_fleet(*, port, host, max_queue, batch_window_s, max_batch,
                 high_water, default_params, breaker_failures,
                 breaker_reset_s, result_keep, journal_dir,
                 journal_sync, envelope_packing, envelope_overhead_ms,
                 pipeline, speculate,
                 session_max, session_segment_cycles,
                 session_checkpoint_every_events,
                 session_certify_after, replicas, affinity,
                 compile_cache_dir, heartbeat_s, probe_timeout_s,
                 spill_slack,
                 hosts, slo_p99_ms, min_replicas, max_replicas,
                 port_file, block) -> Optional["FleetHandle"]:
    """The ``replicas > 1`` serve path: build the worker CLI tail
    from the same kwargs the single-service path consumes (so the two
    cannot drift), spawn the fleet, mount the router front end."""
    from pydcop_tpu.serving.router import FleetRouter, RouterFrontEnd

    params = dict(default_params or {})
    worker_args = [
        "--max_queue", str(max_queue),
        "--batch_window", str(batch_window_s),
        "--max_batch", str(max_batch),
        "--breaker_failures", str(breaker_failures),
        "--breaker_reset", str(breaker_reset_s),
        "--result_keep", str(result_keep),
        "--session_max", str(session_max),
        "--session_checkpoint_every",
        str(session_checkpoint_every_events),
    ]
    if high_water is not None:
        worker_args += ["--high_water", str(high_water)]
    if "max_cycles" in params:
        worker_args += ["--cycles", str(params["max_cycles"])]
    if "damping" in params:
        worker_args += ["--damping", str(params["damping"])]
    # EVERY other default-param key rides as JSON — the fleet and
    # single-service paths must not drift (a replicas=2 service
    # dropping the caller's stability/noise/prune defaults would
    # solve differently than replicas=1 with no error anywhere).
    extra_params = {k: v for k, v in params.items()
                    if k not in ("max_cycles", "damping")}
    if extra_params:
        import json as json_mod

        worker_args += ["--params_json",
                        json_mod.dumps(extra_params)]
    if journal_sync:
        worker_args += ["--journal_sync"]
    if not envelope_packing:
        worker_args += ["--no_envelope"]
    if not pipeline:
        worker_args += ["--no_pipeline"]
    if not speculate:
        worker_args += ["--no_speculate"]
    if envelope_overhead_ms is not None:
        worker_args += ["--envelope_overhead_ms",
                        str(envelope_overhead_ms)]
    if session_segment_cycles is not None:
        worker_args += ["--session_segment_cycles",
                        str(session_segment_cycles)]
    if session_certify_after is not None:
        worker_args += ["--session_certify_after",
                        str(session_certify_after)]
    router = FleetRouter(
        replicas=replicas, worker_args=worker_args,
        journal_dir=journal_dir,
        compile_cache_dir=compile_cache_dir, affinity=affinity,
        heartbeat_s=heartbeat_s, probe_timeout_s=probe_timeout_s,
        spill_slack=spill_slack,
        default_params=params,
        hosts=hosts, slo_p99_ms=slo_p99_ms,
        min_replicas=min_replicas, max_replicas=max_replicas,
    ).start()
    try:
        front_end = RouterFrontEnd(router, port=port,
                                   host=host).start()
    except Exception:
        router.stop(drain=False)
        raise
    handle = FleetHandle(router, front_end)
    import sys

    print(f"pydcop serve: fleet of {replicas} replica(s) behind "
          f"{handle.url} (affinity={affinity})", file=sys.stderr)
    if port_file:
        _write_port_file(port_file, handle.port)
    if not block:
        return handle
    _serve_until_signal(
        handle,
        lambda summary: (
            "pydcop serve: fleet shut down — worker exits "
            + ", ".join(
                f"replica-{w['index']}={w['exit']}"
                for w in summary["workers"])))
    return None


def _serve_until_signal(handle, summarize) -> None:
    """``block=True`` shared tail: wait for SIGTERM/SIGINT, cut the
    black-box bundle, drain-stop the handle, log the summary."""
    import signal
    import sys
    import threading

    stop_event = threading.Event()
    got_signal = []

    def _on_signal(signum, frame):  # noqa: ARG001 — signal signature
        got_signal.append(signum)
        stop_event.set()

    # SIGTERM is what an orchestrator sends before the SIGKILL
    # grace deadline; both it and Ctrl-C route through the same
    # drain-first shutdown.  Original handlers restored on exit so an
    # embedding process is left the way it was found.  Handlers can
    # only be installed from the main thread — a background-thread
    # caller just blocks on the event (signals never reach it).
    previous = {}
    if threading.current_thread() is threading.main_thread():
        previous = {
            sig: signal.signal(sig, _on_signal)
            for sig in (signal.SIGTERM, signal.SIGINT)
        }
    try:
        stop_event.wait()
        print("pydcop serve: signal received, draining…",
              file=sys.stderr)
        # Fatal-signal anomaly: cut the black-box bundle BEFORE the
        # drain mutates the queue/journal — the bundle shows what the
        # process was doing when the orchestrator pulled the plug.
        from pydcop_tpu.observability import flight

        flight.trigger("fatal_signal", force=True,
                       signum=(got_signal[0] if got_signal else None))
    finally:
        summary = handle.stop(drain=True)
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        print(summarize(summary), file=sys.stderr)


def _solve(dcop, algo_def, module, *, distribution, backend, timeout,
           max_cycles, mesh, n_devices, shards, warmup, ui_port,
           collector,
           collect_moment, collect_period, delay, checkpoint_dir,
           checkpoint_every, checkpoint_async, checkpoint_keep,
           resume, fault_plan, recovery, health, observing,
           metrics_file, metrics_every, serving=False) -> SolveResult:
    if backend == "device":
        if not hasattr(module, "solve_on_device"):
            raise NotImplementedError(
                f"Algorithm {algo_def.algo} has no device path; use "
                "backend='thread'"
            )
        # Join the cross-host runtime when configured (PYDCOP_* env
        # vars / PYDCOP_MULTIHOST=auto); single-host runs no-op.
        from pydcop_tpu.engine.multihost import initialize_multihost

        initialize_multihost()
        t0 = time.perf_counter()
        # The engine probe needs chunk boundaries, so an observed solve
        # routes through the same segmented loop checkpointing uses —
        # and decimation IS a segmented mode now (clamping happens at
        # those same boundaries), so decimated solves checkpoint,
        # recover and probe like any other.  Excluded: warmup=True
        # (the segmented loop has no discarded warm-up call, and
        # silently dropping a requested steady-state measurement would
        # be worse than losing the cost curve) — it falls back to the
        # plain path, which still traces the overall device_solve span
        # and routes decimation through solve_on_device's own
        # segmented call.
        decim_plan = None
        if hasattr(module, "decimation_plan_from_params"):
            decim_plan = module.decimation_plan_from_params(
                algo_def.params)
        probed = (
            observing
            and not warmup
            and hasattr(module, "build_engine")
        )
        if checkpoint_dir is not None or probed \
                or recovery is not None \
                or (decim_plan is not None and not warmup):
            if not hasattr(module, "build_engine"):
                raise NotImplementedError(
                    f"Algorithm {algo_def.algo} has no segmentable "
                    "engine: checkpointing/recovery supports "
                    "maxsum-family solves"
                )
            from pydcop_tpu.resilience.checkpoint import (
                CheckpointManager,
                resume_from_checkpoint,
            )

            engine = module.build_engine(
                dcop, algo_def.params, mesh=mesh, n_devices=n_devices,
                shards=shards,
            )
            probe = None
            if probed:
                from pydcop_tpu.observability.engine_probe import (
                    EngineProbe,
                )

                # Snapshots fire at chunk boundaries; with
                # checkpointing the chunk size is the checkpoint
                # cadence, so the effective snapshot period is
                # max(checkpoint_every, metrics_every).
                probe = EngineProbe(
                    engine, metrics_path=metrics_file,
                    metrics_every=metrics_every or 1,
                )
            manager = None
            segment_cycles = None
            if checkpoint_dir is not None:
                manager = CheckpointManager(
                    checkpoint_dir, every=checkpoint_every or 100,
                    keep=checkpoint_keep,
                )
            elif decim_plan is not None:
                # Decimation rounds set the boundary cadence unless
                # an explicit metrics cadence asks for finer points.
                segment_cycles = (metrics_every
                                  or decim_plan.cycles_per_round)
            else:
                segment_cycles = metrics_every or 100
            if resume:
                res = resume_from_checkpoint(
                    engine, manager, max_cycles=max_cycles,
                    probe=probe, checkpoint_async=checkpoint_async,
                    recovery=recovery, decimation=decim_plan,
                )
            else:
                res = engine.run_checkpointed(
                    max_cycles=max_cycles, manager=manager,
                    segment_cycles=segment_cycles, probe=probe,
                    checkpoint_async=checkpoint_async,
                    recovery=recovery, decimation=decim_plan,
                )
            if probe is not None:
                from pydcop_tpu.observability.engine_probe import (
                    attach_result_metrics,
                )

                attach_result_metrics(res, probe)
        else:
            extra = {}
            if shards is not None and shards > 1:
                # Only the maxsum family accepts shards (gated
                # above); other modules never see the kwarg.
                extra["shards"] = shards
            res = module.solve_on_device(
                dcop, algo_def, max_cycles=max_cycles, mesh=mesh,
                n_devices=n_devices, warmup=warmup, **extra,
            )
        cost, violations = dcop.solution_cost(res.assignment)
        return SolveResult(
            status="FINISHED" if res.converged else "TIMEOUT",
            assignment=res.assignment,
            cost=cost,
            violations=violations,
            cycles=res.cycles,
            time=res.time_s,
            compile_time=res.compile_time_s,
            total_time=time.perf_counter() - t0,
            metrics=res.metrics,
            backend="device",
        )

    if backend in ("thread", "process"):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            has_agent_computation,
        )
        from pydcop_tpu.infrastructure.run import solve_with_agents

        # Reject before deployment rather than crashing mid-run on the
        # first build_computation call.
        if not has_agent_computation(algo_def.algo):
            raise NotImplementedError(
                f"Algorithm {algo_def.algo!r} has no agent-mode "
                "computation yet; use backend='device'"
            )

        # Bound non-terminating algorithms: without an explicit timeout a
        # maxsum/dsa run would block forever on the finished event.
        if timeout is None:
            timeout = 15.0
        return solve_with_agents(
            dcop, algo_def, distribution=distribution,
            timeout=timeout, max_cycles=max_cycles, mode=backend,
            ui_port=ui_port, collector=collector,
            collect_moment=collect_moment,
            collect_period=collect_period, delay=delay,
            fault_plan=fault_plan, health_config=health,
            metrics_file=metrics_file, metrics_every=metrics_every,
            metrics_live=serving,
        )

    raise ValueError(f"Unknown backend {backend!r}")

"""Disk-persisted AOT compile cache: cold-start killer for the fleet.

Every fresh ``pydcop serve`` worker used to pay full XLA compilation
for every structure it ever saw — multi-second time-to-first-result
per structure per process, multiplied by the replica count (ROADMAP
open item 2).  This module wires up JAX's on-disk compilation cache so
a compiled executable persists ACROSS processes: the first worker that
compiles a structure's program writes it to ``cache_dir``, and every
later worker (a fresh replica, a crash-restarted one, the next bench
round) deserializes it in tens of milliseconds instead of recompiling.

**The set-before-jit latch.**  JAX latches its cache configuration on
the FIRST jit compilation: setting ``jax_compilation_cache_dir`` after
any jit has run silently no-ops, because the process-wide cache object
was already initialized without a persistent backing store.
:func:`enable_persistent_compile_cache` therefore always calls
``jax._src.compilation_cache.reset_cache()`` after updating the
config — safe before the first jit, REQUIRED after it — and must be
invoked in every worker at spawn, before the accelerator probe or any
other jit (``pydcop serve --compile_cache_dir`` and
``api.serve(compile_cache_dir=...)`` both do; the fleet router passes
the directory to every worker it spawns, so all replicas share one
cache).

**Keying.**  JAX keys cache entries by the serialized HLO + compile
options + backend — a superset of our structure bin key
(serving/binning.bin_key): two same-structure requests lower to the
same HLO (cost tables are runtime operands, never constants), so the
structure key's equivalence classes map onto disk-cache hits.  The
cache composes with the PR-3 layout cache (host-side arrays) and the
per-process jit cache (live executables): layout cache saves host
compile work, this cache saves XLA compile work across processes, the
jit cache saves both within one.

**Hit accounting.**  JAX announces cache activity on its monitoring
bus; we subscribe once and keep process-wide counters so (a) tests and
the bench can assert a fresh process genuinely skipped compilation and
(b) ``timed_jit_call`` can split a cold dispatch honestly: a cold call
whose executables ALL came off the disk cache did not compile — its
ledger ``compile`` component is the measured cache-retrieval wall
(milliseconds), not the whole first-call interval
(:func:`split_cold_call`).  The serve_cold_start bench leg and the
fleet docs (docs/serving.md "Persistent compile cache") build on
exactly this accounting.

``PYDCOP_COMPILE_CACHE_DIR`` enables the cache from the environment
(:func:`maybe_enable_from_env`) — how spawned workers inherit the
router's cache directory without re-plumbing every knob.
"""

import logging
import os
import threading
from typing import Any, Dict, Optional

logger = logging.getLogger("pydcop.engine.aotcache")

ENV_DIR = "PYDCOP_COMPILE_CACHE_DIR"

# JAX monitoring bus keys (jax/_src/compiler.py + compilation_cache.py).
_EVT_HIT = "/jax/compilation_cache/cache_hits"
_EVT_MISS = "/jax/compilation_cache/cache_misses"
_DUR_RETRIEVAL = "/jax/compilation_cache/cache_retrieval_time_sec"
_DUR_SAVED = "/jax/compilation_cache/compile_time_saved_sec"

_lock = threading.Lock()
_state: Dict[str, Any] = {
    "enabled": False,
    "dir": None,
    "hits": 0,
    "misses": 0,
    "retrieval_s": 0.0,
    "saved_s": 0.0,
    "listeners_installed": False,
}


def _on_event(event: str, **kwargs) -> None:
    if event == _EVT_HIT:
        with _lock:
            _state["hits"] += 1
    elif event == _EVT_MISS:
        with _lock:
            _state["misses"] += 1


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if event == _DUR_RETRIEVAL:
        with _lock:
            _state["retrieval_s"] += float(duration)
    elif event == _DUR_SAVED:
        with _lock:
            _state["saved_s"] += float(duration)


def _install_listeners() -> None:
    with _lock:
        if _state["listeners_installed"]:
            return
        _state["listeners_installed"] = True
    from jax._src import monitoring

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)


def enable_persistent_compile_cache(
        cache_dir: Optional[str] = None,
        min_compile_time_s: float = 0.0) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` and
    make the setting stick (the set-before-jit latch: see module
    docstring).  Returns the resolved directory, or None when neither
    the argument nor ``PYDCOP_COMPILE_CACHE_DIR`` names one.

    Call this ONCE, as early as possible — in a serve worker that
    means at spawn, before the accelerator probe.  Calling after a jit
    still works (``reset_cache`` drops the latched in-memory cache so
    the next compile re-reads the config), but every executable
    compiled before the call was never written to disk.

    ``min_compile_time_s`` lowers JAX's default persist threshold
    (1 s) to 0 so the small CPU programs the serve plane compiles are
    cached too — on a fleet the cache exists precisely to make tiny
    per-structure compiles free for the second process.
    """
    cache_dir = cache_dir or os.environ.get(ENV_DIR) or None
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_s))
    try:
        # -1 = no minimum entry size (name differs across jax
        # versions; absence just means the default floor applies).
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    except AttributeError:
        pass
    from jax._src import compilation_cache

    # THE LATCH: config alone is a silent no-op once any jit ran —
    # the process-wide cache object must be rebuilt to pick the
    # directory up.  Safe (idempotent) before the first jit.
    compilation_cache.reset_cache()
    _install_listeners()
    with _lock:
        _state["enabled"] = True
        _state["dir"] = cache_dir
    logger.info("persistent AOT compile cache at %s", cache_dir)
    return cache_dir


def maybe_enable_from_env() -> Optional[str]:
    """Enable iff ``PYDCOP_COMPILE_CACHE_DIR`` is set — the worker-
    spawn hook (the router exports the env var to every replica)."""
    if os.environ.get(ENV_DIR):
        return enable_persistent_compile_cache()
    return None


def enabled() -> bool:
    with _lock:
        return bool(_state["enabled"])


def cache_dir() -> Optional[str]:
    with _lock:
        return _state["dir"]


def counters() -> Dict[str, float]:
    """Monotone counter snapshot (hits/misses/retrieval_s/saved_s) —
    delta two snapshots around a dispatch to attribute ITS cache
    activity (:func:`split_cold_call`)."""
    with _lock:
        return {
            "hits": _state["hits"],
            "misses": _state["misses"],
            "retrieval_s": _state["retrieval_s"],
            "saved_s": _state["saved_s"],
        }


def split_cold_call(elapsed_s: float, before: Dict[str, float],
                    after: Dict[str, float]) -> Optional[float]:
    """Honest ``compile`` seconds for one COLD jit dispatch given the
    counter snapshots around it.

    Returns the compile component to report, or None to keep the
    caller's default convention (cold interval == compile):

    - every executable the dispatch needed came off the disk cache
      (hits advanced, misses did not) → the dispatch did not compile;
      its compile component is the measured retrieval wall, clamped
      into ``[0, elapsed]`` — the serve_cold_start acceptance
      ("compile ≈ 0 with a warm cache") is THIS number;
    - any miss, or no cache activity at all (cache disabled,
      measurement unavailable) → None: the conservative whole-interval
      convention stands.
    """
    if not enabled():
        return None
    d_hits = after["hits"] - before["hits"]
    d_misses = after["misses"] - before["misses"]
    if d_hits <= 0 or d_misses > 0:
        return None
    retrieval = max(after["retrieval_s"] - before["retrieval_s"], 0.0)
    return min(retrieval, max(elapsed_s, 0.0))


def disk_stats(directory: Optional[str]) -> Dict[str, int]:
    """Entry count + byte size of a cache DIRECTORY, independent of
    this process's cache state.  The fleet router never jits, so its
    own ``enabled()`` stays False — but it still owns the shared cache
    dir its workers populate, and reports how warm the fleet's disk
    cache is (how much compile work a scale-up prewarm can skip) from
    here."""
    entries = 0
    size = 0
    if directory:
        try:
            for name in os.listdir(directory):
                if name.endswith("-cache"):
                    entries += 1
                try:
                    size += os.path.getsize(
                        os.path.join(directory, name))
                except OSError:
                    pass
        except OSError:
            pass
    return {"entries": entries, "bytes": size}


def stats() -> Dict[str, Any]:
    """Operator-facing snapshot: config + counters + on-disk size
    (surfaced in /stats on every worker and in the router's fleet
    stats)."""
    out: Dict[str, Any] = dict(counters())
    with _lock:
        out["enabled"] = _state["enabled"]
        out["dir"] = _state["dir"]
    out.update(disk_stats(out["dir"]))
    return out

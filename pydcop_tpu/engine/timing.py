"""Honest device timing under an unreliable async dispatch layer.

Measured on the axon TPU tunnel (round 5): ``jax.block_until_ready``
does NOT reliably block until the computation finishes — a 10-iteration
fori_loop over a 64 MB array "completed" in 0.12 ms while the forced
host fetch of the same result took 2.1 s draining the queue.  Every
wall-clock number taken as ``block_until_ready(fn(x)); elapsed`` on
that platform is therefore a lower bound on nothing: it can measure
pure enqueue cost (bench.py's round-5 1M-var scale leg recorded
"25,871 cycles/s", i.e. 1.9 ms for a program whose modeled HBM traffic
alone needs >20 s at v5e peak bandwidth — 10x *over* the physical
peak, which is how the artifact was caught).

Two tools fix this:

- :func:`sync` forces true completion by fetching the smallest output
  buffer to the host.  Bytes cannot be fetched before they exist, on
  any backend, so this is a real barrier (a scalar fetch costs one
  tunnel round-trip, ~130 ms measured — include it in the timed window
  and the number is end-to-end honest).
- :func:`marginal_seconds_per_cycle` removes the fixed tunnel overhead
  (enqueue + round-trip + fetch, independent of program length) by
  timing the same program at two cycle counts and taking the slope.
  This is the chip's steady-state rate — the number roofline
  utilization claims must be based on, since the fixed latency says
  nothing about HBM streaming.

The reference's benchmarks never face this (torch CUDA synchronize is
reliable; reference pydcop measures host wall-clock around a threaded
runtime, e.g. pydcop/commands/solve.py run timers); an async tunnel is
a TPU-deployment reality, so the timing discipline lives here in the
engine, not in bench scripts.
"""

import os
import time
from typing import Any, Callable, Tuple

import jax
import numpy as np


def sync(out: Any) -> Any:
    """Block until ``out`` (any pytree of jax arrays) has actually been
    computed, then return it unchanged.

    Fetches the smallest leaf to the host: all leaves of one executed
    program materialize together, and a host fetch cannot complete
    before the buffer exists — unlike ``jax.block_until_ready``, which
    the experimental axon platform implements as a no-op/partial sync.
    Cost: one round-trip plus the smallest leaf's transfer (pick your
    outputs so a scalar — cycle counter, convergence flag — is among
    them, which every ops.run_* in this package does).

    PRECONDITION (API contract): every array leaf of ``out`` must be
    an output of the SAME dispatched program (or of its dependency
    chain).  Fetching one leaf proves only *that* program finished; a
    pytree assembled from independent dispatches would leave the
    other programs in flight and silently turn the caller's timing
    back into an enqueue time — exactly the artifact this module
    exists to prevent.  Every call site in this package passes a
    single program's output pytree; keep it that way.

    Debug assertion path: ``PYDCOP_SYNC_DEBUG=1`` fetches EVERY leaf
    (one barrier per distinct buffer source, a true sync regardless
    of the precondition).  Run a suspicious measurement under this
    flag: if the number changes materially, a call site is violating
    the single-program contract.
    """
    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "dtype")]
    if not leaves:
        return out
    if os.environ.get("PYDCOP_SYNC_DEBUG") == "1":
        for leaf in leaves:
            np.asarray(jax.device_get(leaf))
        return out
    smallest = min(leaves, key=lambda a: getattr(a, "size", 1))
    np.asarray(jax.device_get(smallest))
    return out


def timed_call(fn: Callable, *args: Any) -> Tuple[Any, float]:
    """``(out, seconds)`` for one fully-completed call of ``fn``.

    The window closes only after :func:`sync` — end-to-end honest on
    every backend, including the fixed tunnel round-trip.
    """
    t0 = time.perf_counter()
    out = sync(fn(*args))
    return out, time.perf_counter() - t0


def warmed_marginal(make_fn: Callable[[int], Callable], lo: int,
                    hi: int, args: Tuple = (), reps: int = 3,
                    ) -> Tuple[float, float, Any]:
    """Build + warm the two programs, then difference them.

    ``make_fn(n)`` returns a callable (typically jitted) running an
    n-cycle program; it is called once per cycle count, so per-call
    jitting inside it is fine.  Both programs are executed to
    completion once before any timed window (compile + warm), and the
    warm full-length output is returned as the third element so
    callers reuse the result instead of paying another run —
    every ops.run_* here is deterministic given its inputs, so the
    warm output IS the run's result.

    Returns ``(sec_per_cycle, fixed_s, out_hi)``.
    """
    fns = {c: make_fn(c) for c in (lo, hi)}
    outs = {c: sync(f(*args)) for c, f in fns.items()}
    per_cycle, fixed = marginal_seconds_per_cycle(
        lambda c: fns[c](*args), lo, hi, reps=reps)
    return per_cycle, fixed, outs[hi]


def marginal_seconds_per_cycle(
        run_cycles: Callable[[int], Any],
        lo: int, hi: int, reps: int = 3) -> Tuple[float, float]:
    """Steady-state per-cycle seconds via two-point differencing.

    ``run_cycles(n)`` must execute an n-cycle program to completion
    (caller jits per cycle count and calls :func:`sync`; both counts
    must be pre-compiled/warmed by the caller so compile time never
    lands in a timed window).  Returns ``(sec_per_cycle, fixed_s)``
    where ``fixed_s`` is the per-call constant (enqueue + round-trip +
    fetch) implied by the intercept — reported so benches can show how
    much of the end-to-end time is tunnel, not chip.

    Medians over ``reps`` repetitions: round-trip jitter on a tunnel is
    tens of ms, so a single rep can produce a negative slope on fast
    programs; the median plus a floor at 0 keeps the estimate sane.
    ``hi - lo`` should be chosen so the real compute delta dominates
    that jitter (hundreds of cycles minimum for VMEM-resident
    problems).
    """
    if hi <= lo:
        raise ValueError(f"need hi > lo, got lo={lo} hi={hi}")
    t_lo, t_hi = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(run_cycles(lo))
        t_lo.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        sync(run_cycles(hi))
        t_hi.append(time.perf_counter() - t0)
    med_lo = float(np.median(t_lo))
    med_hi = float(np.median(t_hi))
    per_cycle = max((med_hi - med_lo) / (hi - lo), 0.0)
    fixed = max(med_lo - per_cycle * lo, 0.0)
    return per_cycle, fixed

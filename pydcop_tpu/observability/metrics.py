"""Metrics registry: counters / gauges / histograms with Prometheus
text-format export and periodic JSONL snapshots.

One process-wide :data:`registry` serves every instrumented site
(transport counters, queue depth, breaker trips, engine cycle/cost
progress).  Counters are monotone by construction (negative increments
raise), which is what makes the exported cycle counter trustworthy.

Cost discipline: always-on sites (the agent/messaging totals that feed
``Agent.metrics()``) use :class:`BoundMetric` handles — the label key
is computed once at bind time, so a hot-path increment is one dict
update under the metric's lock, the same order of cost as the ad-hoc
dicts it replaces.  Optional detail (per-message-type counters, queue
depth) guards on ``registry.active``, set by ``api.solve`` only when
the caller asked for metrics.

Prometheus output follows the text exposition format (``# HELP`` /
``# TYPE`` preamble per metric, ``name{label="value"} v`` samples,
histograms as cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``)
so a scrape endpoint or pushgateway relay needs no translation.
"""

import json
import math
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    for name in labels:
        if not _LABEL_RE.match(name):
            raise ValueError(f"invalid label name {name!r}")
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    # HELP lines terminate at the newline: per the exposition format
    # only backslash and newline are escaped here (quotes stay raw).
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _format_sample(name: str, key: LabelKey, value: float) -> str:
    if key:
        labels = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
        return f"{name}{{{labels}}} {_format_value(value)}"
    return f"{name} {_format_value(value)}"


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _exemplar_suffix(sample: str, cell: Optional[Tuple]) -> str:
    """Append an OpenMetrics exemplar (``# {trace_id="..."} v ts``)
    to a bucket sample line; plain Prometheus parsers that stop at
    the value are unaffected, OpenMetrics-aware ones pick up the
    trace link."""
    if cell is None:
        return sample
    trace_id, value, unix = cell
    return (f'{sample} # {{trace_id="{_escape(trace_id)}"}} '
            f"{_format_value(value)} {unix:.3f}")


class BoundMetric:
    """A metric handle with its label key pre-computed — the hot-path
    form of ``metric.inc(..., **labels)``."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Metric", key: LabelKey):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0):
        self._metric._update_key(self._key, amount)

    def set(self, value: float):
        self._metric._set_key(self._key, value)

    def value(self) -> float:
        return self._metric._value_key(self._key)


class Metric:
    """Base: a named family of samples keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str = ""):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()
        self._values: Dict[LabelKey, float] = {}

    def bind(self, **labels) -> BoundMetric:
        return BoundMetric(self, _label_key(labels))

    def value(self, **labels) -> float:
        return self._value_key(_label_key(labels))

    def _value_key(self, key: LabelKey) -> float:
        with self._lock:
            return self._values.get(key, 0.0)

    def _update_key(self, key: LabelKey, amount: float):
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def _set_key(self, key: LabelKey, value: float):
        with self._lock:
            self._values[key] = value

    def samples(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())

    def _family_name(self, openmetrics: bool) -> str:
        """The HELP/TYPE family name.  OpenMetrics reserves ``_total``
        as a counter SAMPLE suffix and forbids it in the family name
        (family ``x`` exposes sample ``x_total``); the classic text
        format keeps the full name in both places."""
        if (openmetrics and self.kind == "counter"
                and self.name.endswith("_total")):
            return self.name[:-len("_total")]
        return self.name

    def to_prometheus(self, openmetrics: bool = False) -> List[str]:
        family = self._family_name(openmetrics)
        lines = [f"# HELP {family} {_escape_help(self.help)}",
                 f"# TYPE {family} {self.kind}"]
        lines.extend(
            _format_sample(self.name, key, value)
            for key, value in self.samples()
        )
        return lines

    def snapshot(self) -> List[Dict[str, Any]]:
        return [
            {"labels": dict(key), "value": value}
            for key, value in self.samples()
        ]


class Counter(Metric):
    """Monotone counter: increments only."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        self._update_key(_label_key(labels), amount)

    def _update_key(self, key: LabelKey, amount: float):
        if amount < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {amount})"
            )
        Metric._update_key(self, key, amount)

    def _set_key(self, key: LabelKey, value: float):
        raise ValueError(f"counter {self.name} cannot be set, only inc'd")


class Gauge(Metric):
    """Point-in-time value: set / inc / dec."""

    kind = "gauge"

    def set(self, value: float, **labels):
        self._set_key(_label_key(labels), value)

    def inc(self, amount: float = 1.0, **labels):
        self._update_key(_label_key(labels), amount)

    def dec(self, amount: float = 1.0, **labels):
        self._update_key(_label_key(labels), -amount)


class Histogram(Metric):
    """Cumulative-bucket histogram (Prometheus semantics).

    **Latency exemplars**: ``observe(value, exemplar=trace_id)``
    remembers the last (trace_id, value, unix time) observed per
    NATIVE bucket — the bucket the value lands in, not every
    cumulative bucket above it — so a p99 spike in the exposition is
    one hop from a concrete request trace (``pydcop trace query
    --request <trace_id>``).  Exposed in the text exposition with the
    OpenMetrics exemplar syntax (``... # {trace_id="..."} v ts``), in
    :meth:`snapshot` (the ``/stats`` and JSONL form), and resolvable
    by quantile via :meth:`quantile_exemplar`.
    """

    kind = "histogram"

    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                       10.0, 60.0)

    def __init__(self, name: str, help_text: str = "",
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(buckets or self.DEFAULT_BUCKETS))
        # key -> [per-bucket counts..., +Inf count, sum]
        self._hist: Dict[LabelKey, List[float]] = {}
        # key -> [(trace_id, value, unix) or None] per native bucket
        # (len(buckets) + 1: the last slot is the +Inf bucket).
        self._exemplars: Dict[LabelKey, List[Optional[Tuple]]] = {}

    def _native_bucket(self, value: float) -> int:
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                return i
        return len(self.buckets)

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels):
        key = _label_key(labels)
        with self._lock:
            entry = self._hist.get(key)
            if entry is None:
                entry = [0.0] * (len(self.buckets) + 2)
                self._hist[key] = entry
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    entry[i] += 1
            entry[-2] += 1        # +Inf / total count
            entry[-1] += value    # sum
            if exemplar is not None:
                cells = self._exemplars.get(key)
                if cells is None:
                    cells = [None] * (len(self.buckets) + 1)
                    self._exemplars[key] = cells
                cells[self._native_bucket(value)] = (
                    str(exemplar), float(value), time.time())

    def count(self, **labels) -> float:
        with self._lock:
            entry = self._hist.get(_label_key(labels))
            return entry[-2] if entry else 0.0

    def sum(self, **labels) -> float:
        with self._lock:
            entry = self._hist.get(_label_key(labels))
            return entry[-1] if entry else 0.0

    def to_prometheus(self, openmetrics: bool = False) -> List[str]:
        """Text exposition.  Exemplar suffixes are OPENMETRICS-ONLY
        syntax: the classic Prometheus v0.0.4 text parser errors on
        the ``#`` after a sample value (failing the whole scrape), so
        they are appended only when the caller negotiated the
        OpenMetrics content type (``Accept:
        application/openmetrics-text`` on the /metrics endpoint)."""
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted(self._hist.items())
            exemplars = ({k: list(v)
                          for k, v in self._exemplars.items()}
                         if openmetrics else {})
        for key, entry in items:
            cells = exemplars.get(key)
            for i, bound in enumerate(self.buckets):
                bkey = key + (("le", _format_value(bound)),)
                sample = _format_sample(
                    f"{self.name}_bucket", tuple(sorted(bkey)),
                    entry[i])
                lines.append(_exemplar_suffix(
                    sample, cells[i] if cells else None))
            inf_key = tuple(sorted(key + (("le", "+Inf"),)))
            lines.append(_exemplar_suffix(
                _format_sample(f"{self.name}_bucket", inf_key,
                               entry[-2]),
                cells[-1] if cells else None))
            lines.append(_format_sample(f"{self.name}_sum", key,
                                        entry[-1]))
            lines.append(_format_sample(f"{self.name}_count", key,
                                        entry[-2]))
        return lines

    def quantile_exemplar(self, q: float, **labels
                          ) -> Optional[Dict[str, Any]]:
        """The exemplar of the bucket holding the q-quantile
        observation (e.g. ``q=0.99`` → the p99 bucket), or the
        nearest lower bucket holding one — None when nothing with an
        exemplar was ever observed.  Returns ``{le, trace_id, value,
        unix}``."""
        key = _label_key(labels)
        with self._lock:
            entry = self._hist.get(key)
            cells = self._exemplars.get(key)
        if entry is None or cells is None or entry[-2] <= 0:
            return None
        rank = max(float(q), 0.0) * entry[-2]
        target = len(self.buckets)  # +Inf slot by default
        for i in range(len(self.buckets)):
            if entry[i] >= rank:
                target = i
                break
        les = ([_format_value(b) for b in self.buckets] + ["+Inf"])
        # Prefer the quantile's own bucket; a cumulative count can
        # cross the rank in a bucket whose native observations all
        # lacked exemplars, so fall back to the nearest LOWER bucket
        # that holds one (per the docstring contract — a p99 labeled
        # with a slower-than-p99 exemplar would overstate the tail),
        # and only then look above.
        order = (list(range(target, -1, -1))
                 + list(range(target + 1, len(cells))))
        for i in order:
            if cells[i] is not None:
                trace_id, value, unix = cells[i]
                return {"le": les[i], "trace_id": trace_id,
                        "value": value, "unix": unix}
        return None

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = sorted(self._hist.items())
            exemplars = {k: list(v)
                         for k, v in self._exemplars.items()}
        les = [_format_value(b) for b in self.buckets] + ["+Inf"]
        return [
            {
                "labels": dict(key),
                "count": entry[-2],
                "sum": entry[-1],
                "buckets": {
                    _format_value(b): entry[i]
                    for i, b in enumerate(self.buckets)
                },
                "exemplars": {
                    les[i]: {"trace_id": cell[0], "value": cell[1],
                             "unix": cell[2]}
                    for i, cell in enumerate(
                        exemplars.get(key) or [])
                    if cell is not None
                },
            }
            for key, entry in items
        ]


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors.

    ``active`` gates the *optional* high-cardinality instrumentation
    (per-message-type counters, queue-depth gauges); the always-on
    totals ignore it.  Creation is idempotent; re-registering a name
    as a different kind raises — two subsystems silently sharing a
    name would corrupt both series.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}
        self.active = False

    def _get_or_create(self, cls, name: str, help_text: str, **kwargs):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(name, help_text, **kwargs)
                self._metrics[name] = metric
            elif not isinstance(metric, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{metric.kind}, not {cls.kind}"
                )
            return metric

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[Sequence[float]] = None
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_text,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def value(self, name: str, **labels) -> float:
        metric = self.get(name)
        return metric.value(**labels) if metric is not None else 0.0

    def metrics(self) -> List[Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def to_prometheus(self, openmetrics: bool = False) -> str:
        """Text exposition; ``openmetrics=True`` switches to the
        OpenMetrics dialect (histogram exemplar suffixes + the
        mandatory ``# EOF`` terminator) — only for responses whose
        content type was negotiated as
        ``application/openmetrics-text``."""
        lines: List[str] = []
        for metric in self.metrics():
            lines.extend(metric.to_prometheus(openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self) -> Dict[str, Any]:
        return {
            metric.name: {
                "kind": metric.kind,
                "samples": metric.snapshot(),
            }
            for metric in self.metrics()
        }

    def write_snapshot(self, path: str, **extra):
        """Append one JSONL snapshot line: ``{"ts": ..., **extra,
        "metrics": {...}}``."""
        row = {"ts": time.time(), **extra, "metrics": self.snapshot()}
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(row, default=str) + "\n")

    def reset(self):
        """Drop every metric (tests); ``active`` is untouched."""
        with self._lock:
            self._metrics = {}


registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return registry


def merge_snapshots(snapshots: Dict[str, Dict[str, Any]],
                    label: str = "replica") -> Dict[str, Any]:
    """Merge per-process registry snapshots (``registry.snapshot()``
    shape, keyed by source name) into one snapshot whose every sample
    gains a ``label`` identifying where it came from — the
    ``GET /fleet/metrics`` aggregation.  Summation is deliberately
    NOT done here: keeping the per-source samples (distinguished by
    the label) preserves conservation checks — summing
    ``pydcop_requests_total`` across ``replica`` labels must
    reproduce the router's own admission ledger, which a pre-summed
    view could fake."""
    merged: Dict[str, Any] = {}
    for source in sorted(snapshots):
        snap = snapshots[source] or {}
        for name, family in snap.items():
            out = merged.setdefault(
                name, {"kind": family.get("kind", "untyped"),
                       "samples": []})
            for sample in family.get("samples", []):
                row = dict(sample)
                labels = dict(row.get("labels") or {})
                labels[label] = source
                row["labels"] = labels
                out["samples"].append(row)
    return merged


def render_snapshot_prometheus(merged: Dict[str, Any]) -> str:
    """Prometheus text exposition for a merged snapshot (the
    ``merge_snapshots`` shape).  Counter/gauge samples render
    directly; histogram snapshot rows expand back into
    ``_bucket``/``_sum``/``_count`` series.  HELP lines are omitted —
    help text does not survive the snapshot wire format."""
    lines: List[str] = []
    for name in sorted(merged):
        family = merged[name]
        lines.append(f"# TYPE {name} {family.get('kind', 'untyped')}")
        for sample in family.get("samples", []):
            labels = sample.get("labels") or {}
            base = tuple(sorted(
                (str(k), str(v)) for k, v in labels.items()))
            if "value" in sample:
                lines.append(_format_sample(name, base,
                                            sample["value"]))
                continue
            # Histogram snapshot row: buckets + the implicit +Inf.
            for le, count in sorted(
                    (sample.get("buckets") or {}).items()):
                bkey = tuple(sorted(base + (("le", str(le)),)))
                lines.append(_format_sample(f"{name}_bucket", bkey,
                                            count))
            inf_key = tuple(sorted(base + (("le", "+Inf"),)))
            lines.append(_format_sample(f"{name}_bucket", inf_key,
                                        sample.get("count", 0.0)))
            lines.append(_format_sample(f"{name}_sum", base,
                                        sample.get("sum", 0.0)))
            lines.append(_format_sample(f"{name}_count", base,
                                        sample.get("count", 0.0)))
    return "\n".join(lines) + "\n" if lines else ""


class CycleSnapshotter:
    """Progress recorder shared by both backends: maintains the
    monotone ``pydcop_cycles_total`` counter, the ``pydcop_cycle`` /
    ``pydcop_cost`` gauges, and (optionally) appends a JSONL snapshot
    each time the global cycle advances by ``every``.

    The device engine calls it once per K-cycle chunk (already paced,
    ``every=1``); the threaded orchestrator calls it on every
    cycle-change report and the cadence check here rate-limits the
    writes.  ``cost_fn`` is only invoked when a snapshot actually
    fires, so per-cycle reports never pay a cost evaluation.

    Fired snapshots are also pushed to listeners — per-instance ones
    (:meth:`add_listener`) and the class-wide set
    (:meth:`add_global_listener`), which is how the live telemetry
    endpoint's ``/events`` SSE stream observes whichever snapshotter
    the current run happens to drive without holding a reference to
    it.  Listener errors are swallowed: a slow or dead subscriber
    must never stall the solve.
    """

    # Class-wide listeners: every instance notifies these.
    _global_listeners: List = []
    _global_lock = threading.Lock()

    @classmethod
    def add_global_listener(cls, fn):
        with cls._global_lock:
            cls._global_listeners.append(fn)

    @classmethod
    def remove_global_listener(cls, fn):
        with cls._global_lock:
            if fn in cls._global_listeners:
                cls._global_listeners.remove(fn)

    @classmethod
    def publish(cls, event: Dict[str, Any]):
        """Push one event to every class-wide listener — the shared
        fan-out behind the SSE ``/events`` stream.  Producers other
        than the cycle snapshotters (the serve plane's
        request-lifecycle events) publish here; listener errors are
        swallowed like everywhere else (a dead subscriber must never
        stall the producer)."""
        with cls._global_lock:
            listeners = list(cls._global_listeners)
        for fn in listeners:
            try:
                fn(event)
            except Exception:  # noqa: BLE001 — never stall producers
                pass

    def __init__(self, path: Optional[str] = None, every: int = 1,
                 reg: Optional[MetricsRegistry] = None,
                 cost_fn=None):
        self.path = path
        self.every = max(int(every), 1)
        self.registry = reg if reg is not None else registry
        self.cost_fn = cost_fn
        self._last: Optional[int] = None
        self._lock = threading.Lock()
        self._cycles = self.registry.counter(
            "pydcop_cycles_total",
            "Global solver cycles completed (monotone)")
        self._cycle_g = self.registry.gauge(
            "pydcop_cycle", "Current global solver cycle")
        self._cost_g = self.registry.gauge(
            "pydcop_cost", "Cost of the current best-known assignment")
        self.points: List[Tuple[int, Optional[float]]] = []
        self._listeners: List = []

    def add_listener(self, fn):
        self._listeners.append(fn)

    def __call__(self, cycle: int, cost: Optional[float] = None,
                 **extra):
        """Record one progress point.  ``extra`` (non-None values
        only) rides into the snapshot event — the engine probe adds
        its convergence-health signals (``residual``, ``flip_rate``)
        here so the SSE stream carries them per chunk."""
        cycle = int(cycle)
        with self._lock:
            last = self._last
            if last is not None and cycle - last < self.every:
                return
            delta = cycle - (last or 0)
            if delta <= 0:
                return
            self._last = cycle
        if cost is None and self.cost_fn is not None:
            try:
                cost = self.cost_fn()
            except Exception:
                cost = None
        self._cycles.inc(delta)
        self._cycle_g.set(cycle)
        if cost is not None:
            cost = float(cost)
            self._cost_g.set(cost)
        self.points.append((cycle, cost))
        if self.path:
            self.registry.write_snapshot(self.path, cycle=cycle,
                                         cost=cost)
        event = {"ts": time.time(), "cycle": cycle, "cost": cost}
        for k, v in extra.items():
            if v is not None:
                event[k] = v
        with self._global_lock:
            listeners = self._listeners + self._global_listeners
        for fn in listeners:
            try:
                fn(event)
            except Exception:  # noqa: BLE001 — never stall the solve
                pass

"""CLI error-path tests: bad inputs must produce clean one-line
errors and non-zero exit codes, not tracebacks (dcop_cli.py main's
error handling; reference CLI behaves the same way)."""

import os
import subprocess
import sys

import pytest

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
FIXTURE = os.path.join(INSTANCES, "coloring_chain.yaml")
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def run_cli(args, timeout=60):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli"] + args,
        timeout=timeout, env=ENV, capture_output=True, text=True,
    )


def test_unknown_algorithm_clean_error():
    res = run_cli(["solve", "--algo", "nosuchalgo", FIXTURE])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr
    assert "nosuchalgo" in (res.stderr + res.stdout)


def test_missing_dcop_file():
    res = run_cli(["solve", "--algo", "dsa", "/nonexistent/x.yaml"])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr


def test_malformed_yaml(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("variables: [unclosed\n")
    res = run_cli(["solve", "--algo", "dsa", str(bad)])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr


def test_yaml_with_unknown_variable_in_constraint(tmp_path):
    bad = tmp_path / "bad_ref.yaml"
    bad.write_text("""
name: broken
objective: min
domains:
  d:
    values: [0, 1]
variables:
  v1:
    domain: d
constraints:
  c1:
    type: intention
    function: v1 + v_missing
agents: [a1]
""")
    res = run_cli(["solve", "--algo", "dsa", str(bad)])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr


def test_bad_algo_param_name():
    res = run_cli([
        "solve", "--algo", "dsa", "-p", "nope:1", FIXTURE])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr
    assert "nope" in (res.stderr + res.stdout)


def test_bad_algo_param_value():
    res = run_cli([
        "solve", "--algo", "dsa", "-p", "variant:Z", FIXTURE])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr


def test_unknown_distribution_method():
    res = run_cli([
        "solve", "--algo", "dsa", "--mode", "thread",
        "-d", "nosuchdist", FIXTURE])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr


def test_thread_algo_without_agent_mode_hint():
    """Device-only situations give an actionable message."""
    res = run_cli([
        "run", "-a", "dba", "-m", "device", "-s",
        os.path.join(
            os.path.dirname(__file__), "..", "instances",
            "scenario_remove_a1.yaml"),
        FIXTURE])
    assert res.returncode != 0
    assert "maxsum" in (res.stdout + res.stderr)


def test_graph_command_unknown_graph_model():
    res = run_cli([
        "graph", "--graph", "nosuchgraph", FIXTURE])
    assert res.returncode != 0
    assert "Traceback" not in res.stderr
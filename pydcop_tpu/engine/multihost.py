"""Multi-host (DCN) initialization for the device engine.

Single-host scaling rides ICI through the one-axis mesh in
engine/sharding.py.  Scaling past one host uses JAX's distributed
runtime: every host calls :func:`initialize_multihost` before any jax
call, after which ``jax.devices()`` returns the GLOBAL device list and
the same ``make_mesh()`` / ``shard_graph()`` code paths shard buckets
across hosts — XLA routes the per-superstep all-reduce over ICI within
a slice and DCN across slices.  No engine code changes: the mesh is
just bigger.

This replaces the reference's multi-machine story (one agent process
per machine + JSON-over-HTTP, pydcop/commands/agent.py +
orchestrator.py) for the *data plane*; the HTTP stack remains for
agent-mode deployments and control-plane traffic.

Environment conventions (standard jax.distributed):
- ``PYDCOP_COORDINATOR`` — "host:port" of process 0,
- ``PYDCOP_NUM_PROCESSES`` / ``PYDCOP_PROCESS_ID`` — world size / rank,
- ``PYDCOP_MULTIHOST=auto`` — call ``jax.distributed.initialize()``
  with no arguments, letting it auto-detect the topology (TPU pods).
With none of these set the initializer is a silent single-host no-op,
so the same entry points work everywhere.
"""

import logging
import os
from typing import Optional

logger = logging.getLogger("pydcop.multihost")

_initialized = False


def multihost_initialized() -> bool:
    """True once a join (or single-host no-op) completed successfully."""
    return _initialized


def _reset_initialized():
    """Test hook: forget the latched join state so a fresh
    initialize_multihost attempt runs (the production path never needs
    this — a FAILED join already leaves the latch unset)."""
    global _initialized
    _initialized = False


def _join_with_retry(join, retry_policy, what: str):
    """Run the coordinator join under the retry policy, keeping the
    module un-latched on failure so the caller can try again.

    The coordinator not being up yet surfaces as a raw gRPC
    unavailable error from ``jax.distributed.initialize``; under a
    staggered pod bring-up that is the EXPECTED first-attempt outcome,
    not a fatal one.  On exhaustion the partial distributed client is
    torn down (best effort) and the last error raised.
    """
    from pydcop_tpu.resilience.retry import RetryPolicy

    if retry_policy is None:
        retry_policy = RetryPolicy.from_env(
            "PYDCOP_MULTIHOST_RETRY_",
            max_attempts=5, base_delay=1.0, max_delay=15.0,
            jitter=0.0,
        )

    def _log_retry(attempt, error, delay):
        logger.warning(
            "%s failed (attempt %d: %s); retrying in %.1fs",
            what, attempt, error, delay,
        )

    try:
        retry_policy.call(join, on_retry=_log_retry)
    except Exception:
        import jax

        # A half-joined client would make every later attempt fail
        # with "already initialized"; tear it down so retry is
        # possible.  _initialized stays False (never latched here).
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        raise


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         retry_policy=None) -> bool:
    """Join the JAX distributed runtime (idempotent).

    Arguments default to the ``PYDCOP_*`` environment variables; set
    ``PYDCOP_MULTIHOST=auto`` on TPU pod slices to use
    jax.distributed's no-argument topology auto-detection.  Returns
    True when running distributed (more than one process), False for
    plain single-host runs (nothing configured — a silent no-op).

    The coordinator join runs under ``retry_policy`` (default: built
    from ``PYDCOP_MULTIHOST_RETRY_*`` env vars — exponential backoff,
    5 attempts) because process 0 may simply not be up yet.  On
    failure the module state is NOT latched: a later call retries the
    join instead of silently reporting single-host.
    """
    global _initialized
    if _initialized:
        import jax

        return jax.process_count() > 1

    coordinator_address = (
        coordinator_address or os.environ.get("PYDCOP_COORDINATOR")
    )
    if num_processes is None and "PYDCOP_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["PYDCOP_NUM_PROCESSES"])
    if process_id is None and "PYDCOP_PROCESS_ID" in os.environ:
        process_id = int(os.environ["PYDCOP_PROCESS_ID"])

    import jax

    if coordinator_address is None and num_processes is None:
        if os.environ.get("PYDCOP_MULTIHOST") == "auto":
            # TPU pod: no-arg initialize auto-detects the topology.
            _join_with_retry(
                jax.distributed.initialize, retry_policy,
                "multihost auto-join",
            )
            _initialized = True
            return jax.process_count() > 1
        # Single-host: nothing to join.
        _initialized = True
        return False

    def _join():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    _join_with_retry(
        _join, retry_policy,
        f"multihost join via {coordinator_address}",
    )
    _initialized = True
    logger.info(
        "Joined distributed runtime: process %d/%d, %d global devices",
        jax.process_index(), jax.process_count(),
        len(jax.devices()),
    )
    return jax.process_count() > 1


def fleet_host_id() -> str:
    """Stable identity of the host this process serves from, for the
    fleet control plane (serving/router.py).  Remote replicas announce
    it on ``POST /fleet/join`` so the router can reason about host
    topology (which replicas die together when a machine dies); a
    two-host CI simulation on one box overrides it per process with
    ``PYDCOP_HOST_ID``.  Distinct from the data-plane rank above: a
    serving fleet is N independent single-host engines, not one
    jax.distributed world."""
    host = os.environ.get("PYDCOP_HOST_ID")
    if host:
        return host
    import socket

    return socket.gethostname()


def multihost_configured() -> bool:
    """True when the environment asks for a distributed runtime (the
    ``PYDCOP_*`` conventions above), regardless of whether the join
    has happened yet."""
    return (
        "PYDCOP_COORDINATOR" in os.environ
        or "PYDCOP_NUM_PROCESSES" in os.environ
        or os.environ.get("PYDCOP_MULTIHOST") == "auto"
    )


def global_mesh(n_devices: Optional[int] = None):
    """A mesh over the global (cross-host) device list; call
    :func:`initialize_multihost` first on every host.

    When the environment is CONFIGURED for multihost but the join has
    not completed (never attempted, or the coordinator was lost and
    the retries exhausted), this raises a clean error instead of
    silently building a single-host mesh: a participant sharding over
    its local devices while the rest of the pod shards globally would
    produce a wrong answer, not a crash — the worst failure mode.  The
    un-latched join state (``initialize_multihost`` never latches on
    failure) means the caller can retry the join and come back here.
    """
    from pydcop_tpu.engine.sharding import make_mesh

    if multihost_configured() and not multihost_initialized():
        raise RuntimeError(
            "multihost runtime configured (PYDCOP_COORDINATOR / "
            "PYDCOP_NUM_PROCESSES / PYDCOP_MULTIHOST=auto) but not "
            "initialized: the coordinator join failed or was never "
            "attempted — call initialize_multihost() (it retries and "
            "never latches a failed join) before building a global "
            "mesh"
        )
    return make_mesh(n_devices)


def partitioned_mesh(shards: int):
    """Mesh for the PARTITIONED engine (``api.solve(shards=N)`` /
    ``pydcop solve --shards N``): the same unjoined-multihost guard as
    :func:`global_mesh` (a participant partitioning over its local
    devices while the pod partitions globally would compute a wrong
    halo exchange — the silent-wrong-answer failure mode), plus a
    device-count check with the CPU-testing recipe in the message.

    Under multihost the mesh spans the GLOBAL device list, so cut
    edges between shards on different hosts ride DCN and the rest ICI
    — same code path, bigger mesh."""
    import jax

    if shards < 2:
        raise ValueError(
            f"partitioned sharding needs shards >= 2, got {shards}")
    available = len(jax.devices()) if not multihost_configured() \
        else None
    if available is not None and shards > available:
        raise ValueError(
            f"shards={shards} but only {available} device(s) "
            "available; for CPU testing force host devices with "
            "XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{shards}")
    return global_mesh(shards)

"""Battery over replication/path_utils.py — the pure path-table
algebra under the UCS replica placement (reference
test_replication_path_utils.py depth)."""

import pytest

from pydcop_tpu.replication.path_utils import (
    add_path,
    affordable_path_from,
    before_last,
    cheapest_path_to,
    filter_missing_agents_paths,
    head,
    last,
    remove_path,
)


class TestPathAccessors:
    def test_head(self):
        assert head(("a", "b", "c")) == "a"

    def test_head_empty(self):
        assert head(()) is None

    def test_last(self):
        assert last(("a", "b", "c")) == "c"

    def test_last_single(self):
        assert last(("a",)) == "a"

    def test_last_empty(self):
        assert last(()) is None

    def test_before_last(self):
        assert before_last(("a", "b", "c")) == "b"

    def test_before_last_pair(self):
        assert before_last(("a", "b")) == "a"

    def test_before_last_too_short_raises(self):
        with pytest.raises(IndexError):
            before_last(("a",))
        with pytest.raises(IndexError):
            before_last(())


class TestTableOps:
    def test_add_keeps_sorted(self):
        t = add_path([], 3.0, ("a", "b"))
        t = add_path(t, 1.0, ("a", "c"))
        t = add_path(t, 2.0, ("a", "d"))
        assert [c for c, _ in t] == [1.0, 2.0, 3.0]

    def test_add_is_pure(self):
        t0 = [(1.0, ("a",))]
        t1 = add_path(t0, 0.5, ("b",))
        assert t0 == [(1.0, ("a",))]
        assert len(t1) == 2

    def test_add_equal_costs_both_kept(self):
        t = add_path([(1.0, ("a", "b"))], 1.0, ("a", "c"))
        assert len(t) == 2

    def test_remove_path(self):
        t = [(1.0, ("a", "b")), (2.0, ("a", "c"))]
        t2 = remove_path(t, ("a", "b"))
        assert t2 == [(2.0, ("a", "c"))]
        assert len(t) == 2   # pure

    def test_remove_all_entries_for_path(self):
        t = [(1.0, ("a", "b")), (2.0, ("a", "b"))]
        assert remove_path(t, ("a", "b")) == []

    def test_remove_missing_is_noop(self):
        t = [(1.0, ("a", "b"))]
        assert remove_path(t, ("x",)) == t


class TestQueries:
    TABLE = [
        (1.0, ("o", "a")),
        (2.0, ("o", "a", "b")),
        (3.0, ("o", "c")),
        (4.0, ("o", "a", "d")),
    ]

    def test_cheapest_path_to_hit(self):
        cost, path = cheapest_path_to("b", self.TABLE)
        assert (cost, path) == (2.0, ("o", "a", "b"))

    def test_cheapest_path_to_prefers_lowest_cost(self):
        table = add_path(list(self.TABLE), 0.5, ("o", "x", "b"))
        cost, path = cheapest_path_to("b", table)
        assert cost == 0.5 and path == ("o", "x", "b")

    def test_cheapest_path_to_miss(self):
        cost, path = cheapest_path_to("zz", self.TABLE)
        assert cost == float("inf") and path == ()

    def test_affordable_extends_prefix(self):
        got = affordable_path_from(("o", "a"), 10.0, self.TABLE)
        assert got == [(2.0, ("o", "a", "b")), (4.0, ("o", "a", "d"))]

    def test_affordable_respects_budget(self):
        got = affordable_path_from(("o", "a"), 2.0, self.TABLE)
        assert got == [(2.0, ("o", "a", "b"))]

    def test_affordable_excludes_the_prefix_itself(self):
        got = affordable_path_from(("o", "a"), 10.0, self.TABLE)
        assert (1.0, ("o", "a")) not in got

    def test_affordable_empty_prefix_matches_all_longer(self):
        got = affordable_path_from((), 10.0, self.TABLE)
        assert len(got) == 4

    def test_filter_missing_agents(self):
        got = filter_missing_agents_paths(
            self.TABLE, {"a", "b", "d"})
        # paths through "c" dropped; origin (path[0]) is exempt
        assert (3.0, ("o", "c")) not in got
        assert len(got) == 3

    def test_filter_origin_exempt(self):
        # The origin agent itself need not be in the available set.
        got = filter_missing_agents_paths(
            [(1.0, ("gone", "a"))], {"a"})
        assert got == [(1.0, ("gone", "a"))]

"""On-chip autopilot: convert tunnel luck into a constant-time cost.

Four rounds produced zero driver-captured TPU numbers because the axon
tunnel wedges for hours at a time and a builder had to be at the
keyboard the moment it revived.  This tool removes the keyboard: it
probes the accelerator backend in a bounded loop and, the moment a
probe answers, spends the live tunnel on the queued decision list
unattended:

  1. ``python bench.py``                       — post-dispatch-fix TPU
     headline + the 1M-var HBM scale leg (bench.py self-supervises).
  2. ``python benchmarks/exp_aggregation.py``  — the scatter/sorted/
     boundary A/B whose winner becomes the scale-path default.
  3. ``python benchmarks/exp_allreduce_share.py`` — collective share.
  4. ``python benchmarks/exp_layout.py``       — lane-major vs
     edge-major layout A/B for the HBM-bound regime.

Every probe and every step outcome is appended as a JSON line to
``BENCH_TPU_PROBELOG.jsonl`` (the committed proof that the tunnel
either answered or never did), raw step output is kept under
``benchmarks/runs/``, and each step that *ran on the TPU* gets its
result lines appended to ``BENCH_TPU.md`` under an autopilot section.
Steps whose output comes back ``backend: cpu`` (bench.py falls back by
itself when the tunnel dies mid-run) are NOT marked done — the
autopilot keeps trying them until the deadline.

Usage:
    python tools/onchip_autopilot.py [--deadline-hours H]
        [--interval S] [--once] [--probe-timeout S]

State (which steps have completed on hardware) persists in
``benchmarks/runs/autopilot_state.json`` so a restarted autopilot
resumes instead of re-running finished steps.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
PROBELOG = os.path.join(REPO, "BENCH_TPU_PROBELOG.jsonl")
RUNS_DIR = os.path.join(REPO, "benchmarks", "runs")
STATE = os.path.join(RUNS_DIR, "autopilot_state.json")
BENCH_MD = os.path.join(REPO, "BENCH_TPU.md")

# (name, argv-tail, per-step timeout seconds).  Order = priority; the
# headline bench goes first so a tunnel that wedges again mid-queue
# still leaves the most important number behind.
QUEUE = [
    ("bench", ["bench.py"], 2400),
    # Experiment timeouts sized for the tunnel's remote-compile cost
    # (round 5: 18+ distinct XLA programs at up to 3M edges; a single
    # big compile was observed to take minutes, and exp_aggregation hit
    # its original 3600 s budget before finishing).
    ("exp_aggregation", ["benchmarks/exp_aggregation.py"], 7200),
    ("exp_allreduce_share", ["benchmarks/exp_allreduce_share.py"], 3600),
    ("exp_layout", ["benchmarks/exp_layout.py"], 7200),
]


def log_event(kind, **details):
    event = {"unix": round(time.time(), 1),
             "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
             "event": kind, **details}
    with open(PROBELOG, "a") as fh:
        fh.write(json.dumps(event) + "\n")
    print(f"autopilot: {kind} {details}", file=sys.stderr)
    return event


def load_state():
    try:
        with open(STATE) as fh:
            state = json.load(fh)
        return state if isinstance(state, dict) else {}
    except (OSError, ValueError):
        return {}


def save_state(state):
    os.makedirs(RUNS_DIR, exist_ok=True)
    with open(STATE, "w") as fh:
        json.dump(state, fh, indent=1)


def probe(timeout):
    """One subprocess probe that requires a live *TPU* backend — a
    healthy CPU backend (plugin env unset) must not count, or the
    autopilot would burn hours re-running the whole queue on CPU
    (ran_on_tpu would refuse to retire any step).  A wedged tunnel
    hangs the child forever, hence subprocess + timeout."""
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout, capture_output=True, text=True,
        )
        platform = (proc.stdout or "").strip().splitlines()[-1:]
        platform = platform[0] if platform else ""
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-1:]
            ok, error = False, (
                f"exit {proc.returncode}: {' '.join(tail)[:200]}")
        elif platform != "tpu":
            ok, error = False, f"backend is {platform!r}, not tpu"
        else:
            ok, error = True, None
    except subprocess.TimeoutExpired:
        ok, error = False, f"timeout after {timeout}s"
    log_event("probe", ok=ok, error=error,
              seconds=round(time.time() - t0, 1))
    return ok


def json_lines(text):
    out = []
    for line in (text or "").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


def ran_on_tpu(lines):
    """A step counts as hardware evidence only if every result line
    that declares a backend declares the TPU (bench.py and both
    experiments fall back to CPU by themselves when the tunnel dies
    mid-run — a CPU line must not retire the step)."""
    backends = [ln.get("backend") for ln in lines if "backend" in ln]
    return bool(backends) and all(b == "tpu" for b in backends)


def append_bench_md(name, lines, stamp):
    block = "\n".join(json.dumps(ln) for ln in lines)
    section = (
        f"\n## Round 5 autopilot — {name} ({stamp} UTC, TPU)\n\n"
        f"```json\n{block}\n```\n"
    )
    with open(BENCH_MD, "a") as fh:
        fh.write(section)


def run_step(name, argv_tail, timeout):
    os.makedirs(RUNS_DIR, exist_ok=True)
    stamp = time.strftime("%Y-%m-%dT%H-%M-%S", time.gmtime())
    raw_path = os.path.join(RUNS_DIR, f"{name}_{stamp}.log")
    log_event("step_start", step=name, timeout_s=timeout)
    t0 = time.time()
    # Own session + group kill on timeout: bench.py is itself a
    # supervisor that spawns a grandchild — killing only the direct
    # child would orphan a runner that keeps the tunnel occupied for
    # every later step.
    # PYTHONPATH=REPO: scripts under benchmarks/ get their own dir on
    # sys.path, not the repo root, so `import pydcop_tpu` fails without
    # it (bench.py at the root dodged this; the exp_* steps did not).
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    proc = subprocess.Popen(
        [sys.executable] + argv_tail, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        out, _ = proc.communicate()
        out, rc = out or "", None
    with open(raw_path, "w") as fh:
        fh.write(out)
    lines = json_lines(out)
    on_tpu = ran_on_tpu(lines)
    log_event(
        "step_done", step=name, rc=rc, seconds=round(time.time() - t0, 1),
        result_lines=len(lines), on_tpu=on_tpu, raw=os.path.relpath(
            raw_path, REPO),
    )
    if on_tpu and rc == 0 and lines:
        append_bench_md(name, lines, stamp)
        return True
    return False


def pending_steps(state, log_missing=False):
    pending = []
    for n, a, t in QUEUE:
        if state.get(n, {}).get("done"):
            continue
        if not os.path.exists(os.path.join(REPO, a[0])):
            if log_missing:
                log_event("step_missing", step=n, script=a[0])
            continue
        pending.append((n, a, t))
    return pending


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deadline-hours", type=float, default=11.0)
    ap.add_argument("--interval", type=float, default=300.0,
                    help="seconds between failed probes")
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--once", action="store_true",
                    help="single probe attempt, then exit")
    args = ap.parse_args()

    deadline = time.time() + args.deadline_hours * 3600
    state = load_state()
    log_event("autopilot_start", deadline_hours=args.deadline_hours,
              pending=[n for n, _, _ in pending_steps(state)])

    while time.time() < deadline:
        todo = pending_steps(state)
        if not todo:
            # Completion is only honest if no queued script was
            # silently absent — log any such before declaring done.
            pending_steps(state, log_missing=True)
            log_event("autopilot_complete",
                      done=[n for n in state if state[n].get("done")])
            return 0
        if probe(args.probe_timeout):
            for name, tail, timeout in todo:
                done = run_step(name, tail, timeout)
                if done:
                    state[name] = {
                        "done": True,
                        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                             time.gmtime()),
                    }
                    save_state(state)
                    continue
                # Step failed or fell back to CPU: re-probe before
                # burning the rest of the queue on a dead tunnel.
                if not probe(args.probe_timeout):
                    log_event("tunnel_lost_mid_queue", after=name)
                    break
            if not pending_steps(state):
                # Whole queue retired: loop straight back so the
                # completion branch logs autopilot_complete now, not
                # after an interval sleep (and not as a mislabelled
                # deadline under --once).
                continue
        if args.once:
            break
        remaining = deadline - time.time()
        if remaining <= 0:
            break
        time.sleep(min(args.interval, max(remaining, 0)))

    still = [n for n, _, _ in pending_steps(state)]
    log_event("autopilot_deadline", pending=still)
    return 0 if not still else 1


if __name__ == "__main__":
    sys.exit(main())

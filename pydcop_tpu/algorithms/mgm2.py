"""MGM2: coordinated 2-opt local search (Maheswaran et al. 2004).

Reference parity: pydcop/algorithms/mgm2.py (params :139-143: threshold
0.5, favor unilateral/no/coordinated, stop_cycle; 5-phase semantics
:399-1050).  Kernels: pydcop_tpu/ops/mgm2.py.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'mgm2', max_cycles=30, algo_params={'seed': 1})
    >>> round(res['cost'], 3)
    0.0
"""

from functools import partial
from typing import Optional

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import compile_dcop, validated_aggregation
from pydcop_tpu.engine.runner import DeviceRunResult, run_device_fn
from pydcop_tpu.ops.mgm2 import run_mgm2

GRAPH_TYPE = "constraints_hypergraph"

HEADER_SIZE = 100
UNIT_SIZE = 5

algo_params = [
    # Variable-aggregation strategy for the shared local-search
    # kernels (ops/localsearch.py): "scatter" is the parity
    # default; "ell" replaces every segment_sum/max/min with
    # compile-time dense-gather edge lists (the TPU HBM-regime
    # candidate, benchmarks/exp_aggregation.py).  Single-device;
    # sharded runs always use scatter.
    AlgoParameterDef(
        "aggregation", "str", ["scatter", "ell"], "scatter"
    ),
    AlgoParameterDef("threshold", "float", None, 0.5),
    AlgoParameterDef(
        "favor", "str", ["unilateral", "no", "coordinated"], "unilateral"
    ),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
]


def computation_memory(node) -> float:
    # Two values kept per neighbor: value + gain (mgm2.py:88).
    return len(node.neighbors) * 2 * UNIT_SIZE


def communication_load(src, target: str) -> float:
    # Offer messages carry up to |d_src|*|d_target| (val, val, gain)
    # triples (mgm2.py:91-124).
    target_dom = None
    for c in src.constraints:
        for v in c.dimensions:
            if v.name == target:
                target_dom = len(v.domain)
    if target_dom is None:
        raise ValueError(
            f"target {target!r} is not a neighbor of {src.name}"
        )
    nb_pairs = target_dom * len(src.variable.domain)
    return nb_pairs * UNIT_SIZE * 3 + HEADER_SIZE


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("mgm2", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    from pydcop_tpu.algorithms.mgm import lexic_ranks

    params = algo_def.params
    pad_to = mesh.size if mesh is not None else (n_devices or 1)
    graph, meta = compile_dcop(
        dcop, pad_to=pad_to,
        aggregation=validated_aggregation(params, pad_to))
    cycles = params.get("stop_cycle") or max_cycles
    fn = partial(
        run_mgm2,
        max_cycles=cycles,
        threshold=float(params.get("threshold", 0.5)),
        favor=params.get("favor", "unilateral"),
        lexic_ranks=lexic_ranks(meta),
        seed=params.get("seed", 0),
    )
    return run_device_fn(
        graph, meta, fn, mesh=mesh, n_devices=n_devices, warmup=warmup,
        finished=bool(params.get("stop_cycle")),
    )

"""Pallas TPU kernel for the binary-factor MaxSum update — the hot op
of the flagship benchmark (one min-plus reduction per factor per
direction per superstep).

Layout: DCOP domains are tiny (3-8 values) while factor counts are
huge, so the TPU-friendly layout puts FACTORS on the 128-wide lane
axis and the (domain x domain) cost table on sublanes — every
arithmetic op in the kernel is then a full [n, 128] VPU vector op and
the min-plus reduction unrolls over the (static, tiny) domain:

    costs_T  [D*D, F]   (row d*D+d2 holds costs[:, d, d2])
    msgs_T   [2*D, F]   (row p*D+d holds v2f[:, p, d])
    out_T    [2*D, F]   f2v messages, same layout

    out[0, i] = min_j costs[i, j] + msg[1, j]      (to scope position 0)
    out[1, j] = min_i costs[i, j] + msg[0, i]      (to scope position 1)

(The subtraction of the receiver's own message cancels: it is constant
along the reduced axis, see ops/maxsum.py factor_to_var.)

Honest status: measured on a v5e chip, this kernel runs at parity with
XLA's fusion of the plain jnp expression — the op mix is elementwise
add/min on a tiny minor dimension, which Mosaic cannot schedule better
than XLA already does (see ops/maxsum.py module docstring).  It is
kept as (a) the validated starting point for problem shapes where the
reduction is large enough to be compute-bound (big domains/arities)
and (b) an `interpret=True`-testable reference of the lane-major
layout.  Enable with PYDCOP_PALLAS_MAXSUM=1 (TPU backend only).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128


def _kernel(d: int, c_ref, m_ref, o_ref):
    """One [*, LANES] block: unrolled min-plus over the d x d table."""
    for p in range(2):
        for i in range(d):
            acc = None
            for j in range(d):
                table_row = i * d + j if p == 0 else j * d + i
                msg_row = (1 - p) * d + j
                val = c_ref[table_row, :] + m_ref[msg_row, :]
                acc = val if acc is None else jnp.minimum(acc, val)
            o_ref[p * d + i, :] = acc


@functools.partial(jax.jit, static_argnames=("interpret",))
def binary_factor_update(costs: jnp.ndarray, v2f: jnp.ndarray,
                         interpret: bool = False) -> jnp.ndarray:
    """All factor->variable messages for one arity-2 bucket.

    costs [F, D, D] f32, v2f [F, 2, D] -> f2v [F, 2, D], numerically
    identical to ops.maxsum.factor_to_var for the bucket.
    """
    f, d, _ = costs.shape
    f_pad = -(-f // LANES) * LANES
    costs_t = jnp.transpose(costs, (1, 2, 0)).reshape(d * d, f)
    msgs_t = jnp.transpose(v2f, (1, 2, 0)).reshape(2 * d, f)
    costs_t = jnp.pad(costs_t, ((0, 0), (0, f_pad - f)))
    msgs_t = jnp.pad(msgs_t, ((0, 0), (0, f_pad - f)))

    out_t = pl.pallas_call(
        functools.partial(_kernel, d),
        out_shape=jax.ShapeDtypeStruct((2 * d, f_pad), costs.dtype),
        grid=(f_pad // LANES,),
        in_specs=[
            pl.BlockSpec((d * d, LANES), lambda i: (0, i)),
            pl.BlockSpec((2 * d, LANES), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((2 * d, LANES), lambda i: (0, i)),
        interpret=interpret,
    )(costs_t, msgs_t)

    out = out_t[:, :f].reshape(2, d, f)
    return jnp.transpose(out, (2, 0, 1))

"""Host-side min-edge-cut partitioning of a compiled factor graph.

The replicated-variable sharding story (engine/sharding.shard_graph)
all-reduces dense ``[V+1, D]`` message totals every superstep, so
per-device communication is O(V·D) regardless of how local the graph
is.  The fine-grained factor-graph parallelism analysis (PAPERS.md,
arXiv 1603.02526) and the GPU loopy-BP partition/halo recipe
(arXiv 2509.22337) both say the same thing: partition the graph so
interior message updates stay local and only CUT-EDGE state crosses
devices.  This module is the host side of that recipe:

- :func:`partition_factor_graph` — greedy BFS-growth partitioning with
  boundary refinement (a KL-style gain sweep), no external deps.  BFS
  growth from peripheral (low-degree) seeds produces connected,
  balanced regions; the refinement passes move boundary variables to
  the neighboring shard they are most connected to, under a balance
  cap.  On locally-connected graphs (grids, rings, meshes — the
  sensor-net shapes DCOPs model) this lands single-digit-percent edge
  cuts; on expander-like random graphs no partitioner can do well and
  the reported ``edge_cut_fraction`` says so honestly.

- :class:`Partition` — variable→shard and factor→shard assignments
  plus the cut statistics (``edge_cut_fraction``,
  ``halo_vars_per_shard``, ``balance``) that
  ``DeviceRunResult.metrics`` reports.

- a structure-keyed cache (:data:`partition_cache`), same key material
  as the PR-3 compile layout cache (variable count + per-arity
  scope-index bytes + shard count): re-solving a same-shaped problem
  never re-partitions.

Everything here is pure numpy + stdlib; the device side lives in
engine/sharding.py (:func:`~pydcop_tpu.engine.sharding.
build_partitioned_graph` consumes the Partition).
"""

import os
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclass(frozen=True)
class Partition:
    """A variable/factor → shard assignment with cut statistics.

    ``var_shard`` is ``[V] int32``; ``factor_shard`` holds one
    ``[F_real] int32`` array per bucket (real factors only, padding
    rows excluded, in bucket row order).  ``stats`` carries the
    numbers the engine folds into ``DeviceRunResult.metrics``:

    - ``edge_cut_fraction``: fraction of (factor, variable)
      incidences whose endpoints live on different shards — the
      communication-volume driver;
    - ``halo_vars_per_shard``: per-shard count of variables referenced
      by local factors but owned elsewhere;
    - ``boundary_vars``: size of the global halo-exchange buffer
      (variables that are halo for at least one shard);
    - ``balance``: max owned-variable count over the ideal ``V/S``.
    """

    n_shards: int
    var_shard: np.ndarray
    factor_shard: Tuple[np.ndarray, ...]
    stats: Dict[str, Any] = field(default_factory=dict)


class PartitionCache:
    """Structure-keyed partition memo (same shape as the PR-3
    CompileCache): a partition is a pure function of (variable count,
    per-arity scope indices, shard count), never of costs, so
    same-structure re-solves — the serving traffic pattern — skip the
    BFS + refinement entirely.  Bounded LRU, thread-safe,
    ``PYDCOP_COMPILE_CACHE=0`` disables it together with the layout
    cache (one switch for all structure caching)."""

    def __init__(self, maxsize: int = 8):
        self.maxsize = maxsize
        self._entries: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.builds = 0

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry
            self.misses += 1
            return None

    def put(self, key, entry):
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)

    def record_build(self):
        """Count a real partition construction (cache miss OR caching
        disabled — same convention as the compile cache's
        layout_builds).  Under the lock: serving compiles on
        concurrent submitter threads."""
        with self._lock:
            self.builds += 1

    def clear(self):
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.builds = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "builds": self.builds,
                "entries": len(self._entries),
            }


partition_cache = PartitionCache()


def build_adjacency(scopes: Sequence[np.ndarray], n_vars: int
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSR variable adjacency from per-bucket scope-index arrays
    (``[F, arity] int``): an edge per co-occurring scope pair (factors
    of arity > 2 contribute their scope clique).  Returns
    ``(neighbors, starts, ends)`` — the neighbor list of variable v is
    ``neighbors[starts[v]:ends[v]]`` (duplicates kept: parallel
    factors weigh their pair accordingly in the refinement gains)."""
    pair_blocks: List[np.ndarray] = []
    for sc in scopes:
        if sc.size == 0:
            continue
        arity = sc.shape[1]
        for i in range(arity):
            for j in range(i + 1, arity):
                pair_blocks.append(sc[:, (i, j)])
    if not pair_blocks:
        empty = np.zeros((0,), np.int32)
        zeros = np.zeros((n_vars,), np.int64)
        return empty, zeros, zeros
    pairs = np.concatenate(pair_blocks, axis=0)
    src = np.concatenate([pairs[:, 0], pairs[:, 1]]).astype(np.int64)
    dst = np.concatenate([pairs[:, 1], pairs[:, 0]]).astype(np.int32)
    order = np.argsort(src, kind="stable")
    src, dst = src[order], dst[order]
    idx = np.arange(n_vars)
    starts = np.searchsorted(src, idx, side="left")
    ends = np.searchsorted(src, idx, side="right")
    return dst, starts, ends


def _bfs_grow(n_vars: int, n_shards: int, neighbors: np.ndarray,
              starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    """Initial assignment: grow each shard as a BFS region from a
    peripheral (lowest-degree unassigned) seed until it reaches its
    quota; disconnected leftovers seed fresh BFS frontiers inside the
    same shard.  Quotas are recomputed per shard from the remaining
    pool so the last shard is never starved or flooded."""
    var_shard = np.full(n_vars, -1, np.int32)
    degree = ends - starts
    seed_order = np.argsort(degree, kind="stable")
    seed_ptr = 0
    remaining = n_vars
    for s in range(n_shards):
        if remaining <= 0:
            break
        quota = -(-remaining // (n_shards - s))  # ceil
        size = 0
        frontier: deque = deque()
        while size < quota:
            if not frontier:
                while (seed_ptr < n_vars
                       and var_shard[seed_order[seed_ptr]] >= 0):
                    seed_ptr += 1
                if seed_ptr >= n_vars:
                    break
                frontier.append(int(seed_order[seed_ptr]))
            v = frontier.popleft()
            if var_shard[v] >= 0:
                continue
            var_shard[v] = s
            size += 1
            for u in neighbors[starts[v]:ends[v]]:
                if var_shard[u] < 0:
                    frontier.append(int(u))
        remaining -= size
    # Any stragglers (can only happen on degenerate inputs) land on
    # the last shard so every variable is owned exactly once.
    var_shard[var_shard < 0] = n_shards - 1
    return var_shard


def _refine(var_shard: np.ndarray, n_shards: int,
            neighbors: np.ndarray, starts: np.ndarray,
            ends: np.ndarray, passes: int, imbalance: float
            ) -> np.ndarray:
    """Boundary refinement: deterministic sweeps moving boundary
    variables to the neighboring shard they have the most edges into,
    when that strictly reduces the cut and respects the balance cap.

    Each pass computes every vertex's per-shard connectivity in one
    vectorized scatter-add over the edge list ([V, S] counts — the
    O(V·loop-body) Python sweep would cost minutes at the 1M-variable
    scale this engine targets), selects the positive-gain CANDIDATES
    (an O(cut)-sized set), and applies them in deterministic vertex
    order, re-checking each candidate's gain against the live
    assignment at application time — so every applied move strictly
    reduces the cut (monotone per pass; a candidate stale-ified by an
    earlier move this pass is simply skipped and reconsidered next
    pass), and the loop stops at the first pass that moves nothing."""
    n_vars = var_shard.shape[0]
    if neighbors.size == 0 or n_vars == 0:
        return var_shard
    ideal = n_vars / n_shards
    cap = int(np.ceil(ideal * (1.0 + imbalance)))
    floor = max(1, int(np.floor(ideal * (1.0 - imbalance))))
    sizes = np.bincount(var_shard, minlength=n_shards)
    src = np.repeat(np.arange(n_vars), ends - starts)
    vidx = np.arange(n_vars)
    for _ in range(passes):
        counts = np.zeros((n_vars, n_shards), np.int32)
        np.add.at(counts, (src, var_shard[neighbors]), 1)
        internal = counts[vidx, var_shard]
        counts[vidx, var_shard] = -1
        best = counts.argmax(axis=1)
        gain = counts[vidx, best] - internal
        movers = np.nonzero(gain > 0)[0]
        moved = 0
        for v in movers:
            nb = var_shard[neighbors[starts[v]:ends[v]]]
            cur = int(var_shard[v])
            live = np.bincount(nb, minlength=n_shards)
            live_internal = live[cur]
            live[cur] = -1
            dest = int(np.argmax(live))
            if (live[dest] - live_internal > 0
                    and sizes[dest] < cap and sizes[cur] > floor):
                var_shard[v] = dest
                sizes[cur] -= 1
                sizes[dest] += 1
                moved += 1
        if moved == 0:
            break
    return var_shard


def _assign_factors(scopes: Sequence[np.ndarray],
                    var_shard: np.ndarray
                    ) -> Tuple[np.ndarray, ...]:
    """Each factor goes to the shard owning the majority of its scope
    (its messages then stay local for those endpoints).  Binary
    factors with split endpoints have no majority; alternating the
    tie-break by factor index keeps the cut-factor load balanced
    while staying deterministic."""
    out = []
    for sc in scopes:
        if sc.shape[0] == 0:
            out.append(np.zeros((0,), np.int32))
            continue
        sh = var_shard[sc]  # [F, arity]
        if sc.shape[1] == 1:
            out.append(sh[:, 0].astype(np.int32))
            continue
        if sc.shape[1] == 2:
            idx = np.arange(sh.shape[0])
            pick = np.where(idx % 2 == 0, sh[:, 0], sh[:, 1])
            fac = np.where(sh[:, 0] == sh[:, 1], sh[:, 0], pick)
            out.append(fac.astype(np.int32))
            continue
        counts = np.zeros((sh.shape[0], int(sh.max()) + 1), np.int32)
        rows = np.arange(sh.shape[0])
        for p in range(sh.shape[1]):
            np.add.at(counts, (rows, sh[:, p]), 1)
        out.append(counts.argmax(axis=1).astype(np.int32))
    return tuple(out)


def cut_statistics(scopes: Sequence[np.ndarray],
                   var_shard: np.ndarray,
                   factor_shard: Sequence[np.ndarray],
                   n_shards: int) -> Dict[str, Any]:
    """Cut/halo/balance numbers for a (var, factor) assignment — the
    dict that lands in ``DeviceRunResult.metrics``."""
    n_vars = var_shard.shape[0]
    total = 0
    cut = 0
    halo_sets: List[set] = [set() for _ in range(n_shards)]
    for sc, fs in zip(scopes, factor_shard):
        if sc.shape[0] == 0:
            continue
        vs = var_shard[sc]                      # [F, arity]
        off = vs != fs[:, None]
        total += vs.size
        cut += int(off.sum())
        f_idx, p_idx = np.nonzero(off)
        for f, p in zip(f_idx, p_idx):
            halo_sets[int(fs[f])].add(int(sc[f, p]))
    halo_sizes = [len(h) for h in halo_sets]
    boundary = set().union(*halo_sets) if halo_sets else set()
    sizes = np.bincount(var_shard, minlength=n_shards)
    ideal = n_vars / n_shards if n_shards else 1.0
    return {
        "n_shards": n_shards,
        "edge_cut_fraction": (cut / total) if total else 0.0,
        "cut_incidences": cut,
        "total_incidences": total,
        "halo_vars_per_shard": halo_sizes,
        "boundary_vars": len(boundary),
        "owned_vars_per_shard": sizes.tolist(),
        "balance": float(sizes.max() / ideal) if n_vars else 1.0,
    }


def partition_factor_graph(scopes: Sequence[np.ndarray], n_vars: int,
                           n_shards: int, *, refine_passes: int = 4,
                           imbalance: float = 0.1) -> Partition:
    """Partition a factor graph given per-bucket scope-index arrays.

    Greedy BFS growth (balanced quotas, peripheral seeds) followed by
    ``refine_passes`` boundary-refinement sweeps under a
    ``(1 + imbalance)`` balance cap.  Fully deterministic: no RNG
    anywhere, so the same structure always produces the same
    partition — which is what lets the partition ride the structure
    cache and keeps sharded solves replayable."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    scopes = [np.asarray(sc, np.int64).reshape(-1, sc.shape[-1])
              for sc in scopes]
    if n_shards == 1 or n_vars == 0:
        var_shard = np.zeros(n_vars, np.int32)
        factor_shard = tuple(
            np.zeros(sc.shape[0], np.int32) for sc in scopes)
        return Partition(
            n_shards=n_shards, var_shard=var_shard,
            factor_shard=factor_shard,
            stats=cut_statistics(scopes, var_shard, factor_shard,
                                 n_shards),
        )
    neighbors, starts, ends = build_adjacency(scopes, n_vars)
    var_shard = _bfs_grow(n_vars, n_shards, neighbors, starts, ends)
    var_shard = _refine(var_shard, n_shards, neighbors, starts, ends,
                        refine_passes, imbalance)
    factor_shard = _assign_factors(scopes, var_shard)
    return Partition(
        n_shards=n_shards,
        var_shard=var_shard,
        factor_shard=factor_shard,
        stats=cut_statistics(scopes, var_shard, factor_shard,
                             n_shards),
    )


def real_factor_rows(var_ids: np.ndarray, n_vars: int) -> np.ndarray:
    """Row indices of REAL factors in a (possibly padded) bucket:
    padding rows point every scope slot at the sentinel variable."""
    return np.nonzero(
        ~np.all(np.asarray(var_ids) == n_vars, axis=1))[0]


def partition_compiled(graph, n_shards: int, *,
                       refine_passes: int = 4,
                       imbalance: float = 0.1,
                       use_cache: Optional[bool] = None) -> Partition:
    """Partition a :class:`~pydcop_tpu.engine.compile.
    CompiledFactorGraph` (padding rows excluded), memoized on the
    layout signature — the same (v_count, scope-index bytes) key
    material the PR-3 compile cache uses, extended with the shard
    count."""
    if use_cache is None:
        use_cache = os.environ.get("PYDCOP_COMPILE_CACHE") != "0"
    n_vars = graph.n_vars
    scopes = []
    for b in graph.buckets:
        ids = np.asarray(b.var_ids)
        scopes.append(ids[real_factor_rows(ids, n_vars)])
    key = None
    if use_cache:
        key = (
            n_vars, n_shards, refine_passes, imbalance,
            tuple((sc.shape[1], sc.tobytes()) for sc in scopes),
        )
        hit = partition_cache.get(key)
        if hit is not None:
            return hit
    partition_cache.record_build()
    part = partition_factor_graph(
        scopes, n_vars, n_shards,
        refine_passes=refine_passes, imbalance=imbalance,
    )
    if use_cache:
        partition_cache.put(key, part)
    return part

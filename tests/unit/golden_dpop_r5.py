"""Frozen round-5 copy of the DPOP level-batched UTIL/VALUE sweep
(pydcop_tpu/ops/dpop.py).

Executable perf/semantics baseline for ``test_perf_regression.py``:
the live sweep is raced against this copy IN THE SAME PROCESS (ratio
immune to machine load) and must produce its exact assignment.

Do NOT update this file when optimizing the live sweep unless the
regression test's parity assertion demands it.
"""

from collections import defaultdict
from typing import Any, Dict, List, Tuple

import numpy as np

MAX_NODE_ELEMENTS = 2 ** 26


class GoldenUtilTooLargeError(MemoryError):
    pass


class _NodePlan:
    __slots__ = (
        "name", "dims", "shape", "components", "parent", "depth",
    )

    def __init__(self, name, dims, shape, parent, depth):
        self.name = name
        self.dims = dims
        self.shape = shape
        self.parent = parent
        self.depth = depth
        self.components: Dict[Tuple[int, ...], np.ndarray] = {}

    def add_component(self, axes, array):
        if axes in self.components:
            self.components[axes] = self.components[axes] + array
        else:
            self.components[axes] = array


def _transpose_to_axes(array, positions):
    order = sorted(range(len(positions)), key=lambda i: positions[i])
    axes = tuple(positions[i] for i in order)
    return axes, np.ascontiguousarray(np.transpose(array, order))


def compile_tree(graph, mode):
    from pydcop_tpu.computations_graph.pseudotree import node_depths
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    nodes = {n.name: n for n in graph.nodes}
    depth = node_depths(graph)

    sep: Dict[str, set] = {}
    for name in sorted(nodes, key=lambda n: -depth[n]):
        node = nodes[name]
        s = set()
        for c in node.constraints:
            s.update(v.name for v in c.dimensions)
        for child in node.children:
            s.update(sep[child])
        s.discard(name)
        sep[name] = s

    plans: Dict[str, _NodePlan] = {}
    for name, node in nodes.items():
        var = node.variable
        sep_sorted = sorted(sep[name], key=lambda v: (depth[v], v))
        dims = (name,) + tuple(sep_sorted)
        domain_of = {name: len(var.domain)}
        for c in node.constraints:
            for v in c.dimensions:
                domain_of[v.name] = len(v.domain)
        for child in node.children:
            domain_of[nodes[child].variable.name] = \
                len(nodes[child].variable.domain)
        shape = tuple(
            domain_of.get(d) or len(nodes[d].variable.domain)
            for d in dims
        )
        n_elements = int(np.prod(shape, dtype=np.int64))
        if n_elements > MAX_NODE_ELEMENTS:
            raise GoldenUtilTooLargeError(name)
        plan = _NodePlan(name, dims, shape, node.parent, depth[name])
        pos = {d: i for i, d in enumerate(dims)}
        plan.add_component(
            (0,), np.asarray(var.cost_vector(), dtype=np.float32)
        )
        for c in node.constraints:
            dense = NAryMatrixRelation.from_func_relation(c)
            positions = [pos[v.name] for v in dense.dimensions]
            axes, arr = _transpose_to_axes(
                np.asarray(dense.matrix, dtype=np.float32), positions
            )
            plan.add_component(axes, arr)
        plans[name] = plan
    return plans


_KERNEL_CACHE: Dict[Tuple, Any] = {}


def _kernel_for(signature):
    if signature in _KERNEL_CACHE:
        return _KERNEL_CACHE[signature]
    if len(_KERNEL_CACHE) >= 512:
        _KERNEL_CACHE.clear()
    import jax
    import jax.numpy as jnp

    shape, axes_tuples, mode, want_util = signature
    k = len(shape)

    def kernel(*comps):
        n = comps[0].shape[0]
        acc = jnp.zeros((n,) + shape, dtype=jnp.float32)
        for comp, axes in zip(comps, axes_tuples):
            newshape = (n,) + tuple(
                shape[i] if i in axes else 1 for i in range(k)
            )
            acc = acc + comp.reshape(newshape)
        if not want_util:
            return acc, None
        util = (
            jnp.min(acc, axis=1) if mode == "min"
            else jnp.max(acc, axis=1)
        )
        return acc, util

    _KERNEL_CACHE[signature] = jax.jit(kernel)
    return _KERNEL_CACHE[signature]


def solve_sweep(graph, mode="min"):
    plans = compile_tree(graph, mode)
    nodes = {n.name: n for n in graph.nodes}
    by_level: Dict[int, List[str]] = defaultdict(list)
    for name, plan in plans.items():
        by_level[plan.depth].append(name)
    max_depth = max(by_level) if by_level else 0

    joined: Dict[str, np.ndarray] = {}
    for level in range(max_depth, -1, -1):
        buckets: Dict[Tuple, List[str]] = defaultdict(list)
        for name in by_level[level]:
            plan = plans[name]
            axes_tuples = tuple(sorted(plan.components))
            want_util = plan.parent is not None
            key = (plan.shape, axes_tuples, mode, want_util)
            buckets[key].append(name)
        for key, names in sorted(buckets.items()):
            shape, axes_tuples, _, want_util = key
            stacked = [
                np.stack(
                    [plans[n].components[axes] for n in names]
                )
                for axes in axes_tuples
            ]
            acc, util = _kernel_for(key)(*stacked)
            acc_np = np.asarray(acc)
            util_np = None if util is None else np.asarray(util)
            for i, name in enumerate(names):
                plan = plans[name]
                joined[name] = acc_np[i]
                if want_util:
                    parent_plan = plans[plan.parent]
                    ppos = {
                        d: j for j, d in enumerate(parent_plan.dims)
                    }
                    positions = [ppos[d] for d in plan.dims[1:]]
                    axes, arr = _transpose_to_axes(
                        util_np[i], positions
                    )
                    parent_plan.add_component(axes, arr)

    assignment: Dict[str, Any] = {}
    argopt = np.argmin if mode == "min" else np.argmax
    for level in range(0, max_depth + 1):
        for name in sorted(by_level[level]):
            plan = plans[name]
            var = nodes[name].variable
            idx = tuple(
                nodes[d].variable.domain.index(assignment[d])
                for d in plan.dims[1:]
            )
            vec = joined[name][(slice(None),) + idx]
            assignment[name] = var.domain[int(argopt(vec))]
    return assignment

"""Battery over dcop/dcop.py — the DCOP container: registration,
merge, solution_cost semantics, initial assignments, filter_dcop."""

import pytest

from pydcop_tpu.dcop.dcop import DCOP, filter_dcop
from pydcop_tpu.dcop.objects import (
    AgentDef,
    Domain,
    ExternalVariable,
    Variable,
    VariableWithCostDict,
)
from pydcop_tpu.dcop.relations import (
    UnaryFunctionRelation,
    constraint_from_str,
)

d2 = Domain("d", "", [0, 1])


def coloring():
    v1, v2 = Variable("v1", d2), Variable("v2", d2)
    c = constraint_from_str("c1", "1 if v1 == v2 else 0", [v1, v2])
    dcop = DCOP("t")
    dcop.add_constraint(c)
    return dcop, v1, v2


class TestRegistration:
    def test_invalid_objective_rejected(self):
        with pytest.raises(ValueError, match="min or max"):
            DCOP("t", objective="maximize")

    def test_add_constraint_registers_variables_and_domains(self):
        dcop, v1, v2 = coloring()
        assert set(dcop.variables) == {"v1", "v2"}
        assert d2.name in dcop.domains
        assert dcop.variable("v1") is v1
        assert dcop.constraint("c1").arity == 2

    def test_add_constraint_registers_external_variables(self):
        e = ExternalVariable("sensor", d2, value=1)
        v = Variable("v1", d2)
        c = constraint_from_str("c1", "v1 + sensor", [v, e])
        dcop = DCOP("t")
        dcop.add_constraint(c)
        assert "sensor" in dcop.external_variables
        assert "sensor" not in dcop.variables
        assert dcop.get_external_variable("sensor") is e

    def test_add_agents_forms(self):
        dcop = DCOP("t")
        dcop.add_agents(AgentDef("a1"))
        dcop.add_agents([AgentDef("a2"), AgentDef("a3")])
        dcop.add_agents({"a4": AgentDef("a4")})
        assert set(dcop.agents) == {"a1", "a2", "a3", "a4"}
        assert dcop.agent("a2").name == "a2"

    def test_all_variables(self):
        dcop, v1, v2 = coloring()
        assert set(v.name for v in dcop.all_variables) == {"v1", "v2"}


class TestMerge:
    def test_merge_combines_everything(self):
        d1, *_ = coloring()
        d1.add_agents(AgentDef("a1"))
        v3 = Variable("v3", d2)
        c2 = UnaryFunctionRelation("c2", v3, lambda x: x)
        d2_ = DCOP("other")
        d2_.add_constraint(c2)
        d2_.add_agents(AgentDef("a2"))
        merged = d1 + d2_
        assert set(merged.variables) == {"v1", "v2", "v3"}
        assert set(merged.constraints) == {"c1", "c2"}
        assert set(merged.agents) == {"a1", "a2"}
        assert merged.name == "t+other"

    def test_merge_objective_mismatch_raises(self):
        with pytest.raises(ValueError, match="objective"):
            DCOP("a", "min") + DCOP("b", "max")


class TestSolutionCost:
    def test_constraint_and_variable_costs_summed(self):
        v1 = VariableWithCostDict("v1", d2, {0: 0.5, 1: 2.0})
        v2 = Variable("v2", d2)
        c = constraint_from_str("c1", "3 * (v1 == v2)", [v1, v2])
        dcop = DCOP("t")
        dcop.add_variable(v1)
        dcop.add_constraint(c)
        cost, violations = dcop.solution_cost({"v1": 0, "v2": 0})
        assert cost == 3.5 and violations == 0
        cost, violations = dcop.solution_cost({"v1": 0, "v2": 1})
        assert cost == 0.5

    def test_hard_violations_counted_not_summed(self):
        dcop, *_ = coloring()
        hard = constraint_from_str(
            "h1", "float('inf') if v1 == 1 else 0",
            list(dcop.variables.values()))
        dcop.add_constraint(hard)
        cost, violations = dcop.solution_cost({"v1": 1, "v2": 0})
        assert violations == 1
        assert cost == 0.0   # the inf did not pollute the sum

    def test_missing_variable_raises(self):
        dcop, *_ = coloring()
        with pytest.raises(ValueError, match="Missing variable"):
            dcop.solution_cost({"v1": 0})

    def test_external_variables_filled_from_current_value(self):
        e = ExternalVariable("sensor", d2, value=1)
        v = Variable("v1", d2)
        c = constraint_from_str("c1", "10 * sensor + v1", [v, e])
        dcop = DCOP("t")
        dcop.add_constraint(c)
        cost, _ = dcop.solution_cost({"v1": 1})
        assert cost == 11
        e.value = 0
        cost, _ = dcop.solution_cost({"v1": 1})
        assert cost == 1


class TestInitialAssignment:
    def test_uses_initial_value_else_first_domain_value(self):
        v1 = Variable("v1", d2, initial_value=1)
        v2 = Variable("v2", d2)
        dcop = DCOP("t")
        dcop.add_variable(v1)
        dcop.add_variable(v2)
        assert dcop.initial_assignment() == {"v1": 1, "v2": 0}


class TestFilter:
    def _dcop_with_orphan(self):
        dcop, v1, v2 = coloring()
        orphan = Variable("lonely", d2)
        dcop.add_variable(orphan)
        unary_target = Variable("v9", d2)
        dcop.add_constraint(UnaryFunctionRelation(
            "u9", unary_target, lambda x: x))
        dcop.add_agents(AgentDef("a1"))
        return dcop

    def test_filter_drops_unconstrained_and_unary_only(self):
        filtered = filter_dcop(self._dcop_with_orphan())
        assert set(filtered.variables) == {"v1", "v2"}
        assert set(filtered.constraints) == {"c1"}
        assert "a1" in filtered.agents   # agents preserved

    def test_filter_accept_unary_keeps_unary_scope(self):
        filtered = filter_dcop(
            self._dcop_with_orphan(), accept_unary=True)
        assert "v9" in filtered.variables
        assert "u9" in filtered.constraints
        assert "lonely" not in filtered.variables

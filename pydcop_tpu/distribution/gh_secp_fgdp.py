"""gh_secp_fgdp: SECP-specialized greedy heuristic, factor graph.

Reference parity: pydcop/distribution/gh_secp_fgdp.py:92-198.  SECPs
modeled as factor graphs have four computation kinds, placed in order:

1. actuator variables (hosting cost 0) pinned on their agent, each
   pulling its ``c_<actuator>`` energy cost factor along;
2. every remaining variable is a physical-model variable ``m`` whose
   defining factor is ``c_<m>``: the pair is placed *together* on the
   agent hosting the most of the factor's neighbors (with capacity for
   both footprints);
3. the remaining factors are rule factors, placed one at a time by the
   same neighbor-affinity rule.
"""

from pydcop_tpu.distribution import oilp_secp_fgdp
from pydcop_tpu.distribution.objects import (
    Distribution,
    ImpossibleDistributionException,
)
from pydcop_tpu.distribution.secp_rules import (
    pin_actuators,
    place_by_affinity,
    split_fg_nodes,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None, **_):
    if computation_memory is None:
        raise ImpossibleDistributionException(
            "gh_secp_fgdp requires a computation_memory function")
    agentsdef = list(agentsdef)
    variables, factors = split_fg_nodes(computation_graph)
    mapping, capa, model_vars, factors = pin_actuators(
        computation_graph, agentsdef, computation_memory,
        candidates=variables, cost_factors=factors,
    )

    # Model (factor, variable) pairs; whatever factors remain are rules.
    models = []
    for model_var in model_vars:
        paired = f"c_{model_var}"
        if paired in factors:
            models.append((paired, model_var))
            factors.remove(paired)
    rules = factors

    place_by_affinity(
        computation_graph, computation_memory, mapping, capa, models)
    place_by_affinity(
        computation_graph, computation_memory, mapping, capa,
        [(r,) for r in rules],
    )
    return Distribution({a: list(cs) for a, cs in mapping.items()})


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return oilp_secp_fgdp.distribution_cost(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load)

"""SECP (Smart Environment Configuration Problem) generator —
smart-lighting scenes.

Reference parity: pydcop/commands/generators/secp.py: lights are
variables over levels 0-4 with linear energy cost (:306-322); each model
is a variable plus a hard defining constraint tying it to a weighted sum
of lights (:201-236); rules are soft constraints setting targets for
lights/models (:238-303); one agent per light (:178-198).
"""

from typing import Optional

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str


def generate_secp(
    lights: int,
    models: int,
    rules: int,
    capacity: Optional[int] = None,
    max_model_size: int = 3,
    max_rule_size: int = 3,
    seed: Optional[int] = None,
) -> DCOP:
    rng = np.random.default_rng(seed)
    light_domain = Domain("light", "light", list(range(5)))
    dcop = DCOP(f"secp_{lights}_{models}_{rules}", objective="min")

    light_vars = {}
    for i in range(lights):
        v = Variable(f"l{i}", light_domain)
        light_vars[v.name] = v
        dcop.add_variable(v)
        efficiency = int(rng.integers(1, 10)) / 10
        dcop.add_constraint(constraint_from_str(
            f"c_l{i}", f"{efficiency} * l{i}", [v]))

    model_vars = {}
    for j in range(models):
        mv = Variable(f"m{j}", light_domain)
        model_vars[mv.name] = mv
        dcop.add_variable(mv)
        size = int(rng.integers(2, max(3, max_model_size + 1)))
        chosen = rng.choice(
            list(light_vars), size=min(size, lights), replace=False)
        parts = []
        for name in chosen:
            impact = int(rng.integers(1, 8)) / 10
            parts.append(f"{name} * {impact}")
        expression = (
            f"0 if 10 * abs(m{j} - ({' + '.join(parts)})) < 5 else 10000"
        )
        dcop.add_constraint(constraint_from_str(
            f"c_m{j}", expression,
            list(light_vars.values()) + [mv],
        ))

    all_vars = {**light_vars, **model_vars}
    for k in range(rules):
        max_size = min(max_rule_size, len(all_vars))
        size = int(rng.integers(1, max_size + 1))
        chosen = rng.choice(list(all_vars), size=size, replace=False)
        parts = [
            f"abs({name} - {int(rng.integers(0, 5))} )" for name in chosen
        ]
        dcop.add_constraint(constraint_from_str(
            f"r_{k}", f"10 * ({' + '.join(parts)})",
            list(all_vars.values()),
        ))

    # One agent per light with hosting cost 0 for its own light variable
    # and the light's cost factor — the pinning convention every SECP
    # distribution method relies on (reference generators/secp.py:178-198
    # build_agents: hosting_costs={light: 0, light_cost: 0},
    # default_hosting_cost=100).
    extra = {"capacity": capacity} if capacity else {}
    dcop.add_agents([
        AgentDef(
            f"a{i}",
            hosting_costs={f"l{i}": 0, f"c_l{i}": 0},
            default_hosting_cost=100,
            **extra,
        )
        for i in range(lights)
    ])
    return dcop

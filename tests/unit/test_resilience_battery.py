"""Resilience battery: chaos, checkpoint/resume and retry hardening.

Covers the resilience subsystem end to end (docs/resilience.md):

- RetryPolicy / CircuitBreaker semantics (resilience/retry.py);
- deterministic fault injection (resilience/faults.py);
- checkpoint determinism: a solve interrupted at a segment boundary
  and resumed yields the SAME assignment, cost and cycle count as the
  uninterrupted run (CPU backend, tier-1);
- chaos convergence: MaxSum (async) and DSA under seeded message
  drop / duplicate / delay still reach the fault-free cost;
- kill-and-repair: an agent murdered mid-solve under 10% drop has its
  computation migrated through the replication/reparation path and the
  orchestrated solve completes at the fault-free cost;
- transport hardening: HTTP delivery failure degrades to a Discovery
  dead-agent mark (never an exception on the agent thread), the
  multihost coordinator join retries and never latches on failure, and
  Messaging's shutdown contract (no silent drop, no wait past
  shutdown).

``make chaos`` runs this file with a fixed PYDCOP_CHAOS_SEED; the
fault pattern is a pure function of (seed, edge, message index), so a
failure reproduces under the same seed.
"""

import os
import threading
import time

import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    MSG_MGT,
    CommunicationLayer,
    ComputationMessage,
    InProcessCommunicationLayer,
    Messaging,
)
from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.resilience.checkpoint import (
    CheckpointManager,
    load_state,
    resume_from_checkpoint,
    save_state,
)
from pydcop_tpu.resilience.faults import (
    CrashEvent,
    FaultPlan,
    FaultyCommunicationLayer,
)
from pydcop_tpu.resilience.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryExhaustedError,
    RetryPolicy,
)

CHAOS_SEED = int(os.environ.get("PYDCOP_CHAOS_SEED", "42"))

# Distinct from test_http_transport.py's 19410-19470 range.
PORTS = iter(range(19700, 19760))


# ------------------------------------------------------------------ #
# fixtures


def _coloring_dcop(n_agents=5, n_vars=4):
    """3-colorable chain: fault-free optimum cost is 0."""
    d = Domain("colors", "", ["R", "G", "B"])
    dcop = DCOP("chaos", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(n_vars - 1):
        dcop.add_constraint(constraint_from_str(
            f"diff_{i}_{i + 1}",
            f"10 if v{i} == v{i + 1} else 0",
            [variables[i], variables[i + 1]],
        ))
    dcop.add_agents([
        AgentDef(f"a{i}", capacity=100, default_hosting_cost=i)
        for i in range(n_agents)
    ])
    return dcop


def _variable_distribution():
    return Distribution({
        "a0": ["v0"], "a1": ["v1"], "a2": ["v2"], "a3": ["v3"],
        "a4": [],
    })


def _ring_dcop(n_vars=6):
    """Loopy ring + one chord, for the device engine (not a tree, so
    the solve needs a couple dozen cycles — room to interrupt)."""
    d = Domain("c", "", list(range(3)))
    dcop = DCOP("ckpt", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)] + [(0, 3)]
    for i, j in edges:
        dcop.add_constraint(constraint_from_str(
            f"c{i}_{j}", f"10 if v{i} == v{j} else 0",
            [variables[i], variables[j]],
        ))
    return dcop


def _msg(prio=MSG_ALGO, content="x"):
    return ComputationMessage(
        "c_src", "c_dst", Message("test", content), prio)


class RecordingLayer(CommunicationLayer):
    """Inner transport stub: records sends, delivers nothing."""

    def __init__(self):
        super().__init__()
        self.sent = []

    @property
    def address(self):
        return self

    def send_msg(self, src_agent, dest_agent, msg, on_error=None):
        self.sent.append((src_agent, dest_agent, msg))


# ------------------------------------------------------------------ #
# RetryPolicy / CircuitBreaker


class TestRetryPolicy:
    def test_backoff_is_exponential_and_capped(self):
        policy = RetryPolicy(base_delay=0.1, multiplier=2.0,
                             max_delay=0.5, jitter=0.0)
        delays = [policy.delay_for(a) for a in range(1, 6)]
        assert delays == [0.1, 0.2, 0.4, 0.5, 0.5]

    def test_jitter_is_bounded_and_seeded(self):
        import random

        policy = RetryPolicy(base_delay=1.0, jitter=0.5)
        d1 = policy.delay_for(1, random.Random(7))
        d2 = policy.delay_for(1, random.Random(7))
        assert d1 == d2
        assert 1.0 <= d1 <= 1.5

    def test_call_retries_until_success(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("down")
            return "up"

        policy = RetryPolicy(max_attempts=5, base_delay=0.001,
                             jitter=0.0)
        assert policy.call(flaky) == "up"
        assert len(calls) == 3

    def test_call_exhausts_attempts(self):
        policy = RetryPolicy(max_attempts=3, base_delay=0.001,
                             jitter=0.0)
        with pytest.raises(RetryExhaustedError) as exc:
            policy.call(lambda: (_ for _ in ()).throw(OSError("x")))
        assert isinstance(exc.value.last_error, OSError)

    def test_deadline_stops_before_max_attempts(self):
        policy = RetryPolicy(max_attempts=1000, base_delay=0.2,
                             jitter=0.0, deadline=0.1)
        calls = []

        def failing():
            calls.append(1)
            raise OSError("x")

        with pytest.raises(RetryExhaustedError):
            policy.call(failing)
        assert len(calls) == 1  # next backoff would cross the deadline

    def test_call_requires_a_bound(self):
        policy = RetryPolicy(max_attempts=None, deadline=None)
        with pytest.raises(ValueError):
            policy.call(lambda: None)

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("PYDCOP_RETRY_MAX_ATTEMPTS", "9")
        monkeypatch.setenv("PYDCOP_RETRY_BASE_DELAY", "0.25")
        monkeypatch.setenv("PYDCOP_RETRY_DEADLINE", "12")
        policy = RetryPolicy.from_env("PYDCOP_RETRY_")
        assert policy.max_attempts == 9
        assert policy.base_delay == 0.25
        assert policy.deadline == 12
        # Unset vars keep the passed defaults.
        policy = RetryPolicy.from_env("PYDCOP_OTHER_", max_attempts=2)
        assert policy.max_attempts == 2


class TestCircuitBreaker:
    def test_opens_after_threshold_then_half_opens(self):
        breaker = CircuitBreaker(failure_threshold=3,
                                 reset_timeout=0.1)
        for _ in range(3):
            assert breaker.allow()
            breaker.record_failure()
        assert breaker.state == "open"
        assert not breaker.allow()
        time.sleep(0.12)
        assert breaker.state == "half_open"
        # Exactly one probe allowed, and a success closes the circuit.
        assert breaker.allow()
        assert not breaker.allow()
        breaker.record_success()
        assert breaker.state == "closed"
        assert breaker.allow()

    def test_failed_probe_rearms_timeout(self):
        breaker = CircuitBreaker(failure_threshold=1,
                                 reset_timeout=0.1)
        breaker.record_failure()
        time.sleep(0.12)
        assert breaker.allow()  # probe
        breaker.record_failure()
        assert breaker.state == "open"
        assert not breaker.allow()

    def test_policy_call_respects_breaker(self):
        breaker = CircuitBreaker(failure_threshold=1,
                                 reset_timeout=60.0)
        policy = RetryPolicy(max_attempts=1)
        with pytest.raises(RetryExhaustedError):
            policy.call(
                lambda: (_ for _ in ()).throw(OSError("x")),
                breaker=breaker,
            )
        with pytest.raises(CircuitOpenError):
            policy.call(lambda: "never runs", breaker=breaker)


# ------------------------------------------------------------------ #
# Fault injection


class TestFaultyLayer:
    def _layer(self, plan):
        inner = RecordingLayer()
        return FaultyCommunicationLayer(inner, plan), inner

    def test_same_seed_same_fault_pattern(self):
        outcomes = []
        for _ in range(2):
            layer, inner = self._layer(
                FaultPlan(seed=CHAOS_SEED, drop=0.3))
            for i in range(50):
                layer.send_msg("a", "b", _msg(content=i))
            outcomes.append(
                [m.msg.content for _, _, m in inner.sent])
        assert outcomes[0] == outcomes[1]
        assert 0 < len(outcomes[0]) < 50  # some dropped, some not

    def test_different_seed_different_pattern(self):
        patterns = []
        for seed in (1, 2):
            layer, inner = self._layer(FaultPlan(seed=seed, drop=0.5))
            for i in range(60):
                layer.send_msg("a", "b", _msg(content=i))
            patterns.append([m.msg.content for _, _, m in inner.sent])
        assert patterns[0] != patterns[1]

    def test_drop_one_drops_everything(self):
        layer, inner = self._layer(FaultPlan(drop=1.0))
        for _ in range(10):
            layer.send_msg("a", "b", _msg())
        assert inner.sent == []
        assert layer.stats.dropped == 10

    def test_duplicate_one_delivers_twice(self):
        layer, inner = self._layer(FaultPlan(duplicate=1.0))
        layer.send_msg("a", "b", _msg())
        assert len(inner.sent) == 2
        assert layer.stats.duplicated == 1

    def test_delay_delivers_later(self):
        layer, inner = self._layer(
            FaultPlan(delay=1.0, delay_time=0.05))
        layer.send_msg("a", "b", _msg())
        assert inner.sent == []  # not yet
        deadline = time.monotonic() + 2
        while not inner.sent and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(inner.sent) == 1
        assert layer.stats.delayed == 1

    def test_partition_blocks_cross_group_only(self):
        plan = FaultPlan(partitions=(
            frozenset({"a", "b"}), frozenset({"c"})))
        layer, inner = self._layer(plan)
        layer.send_msg("a", "b", _msg())   # same group
        layer.send_msg("a", "c", _msg())   # cross group
        layer.send_msg("a", "x", _msg())   # x in no group: free
        assert len(inner.sent) == 2
        assert layer.stats.partitioned == 1

    def test_management_traffic_protected(self):
        layer, inner = self._layer(FaultPlan(drop=1.0))
        layer.send_msg("a", "b", _msg(prio=MSG_MGT))
        assert len(inner.sent) == 1
        layer.send_msg("a", "b", _msg(prio=MSG_ALGO))
        assert len(inner.sent) == 1  # algo message dropped

    def test_crash_event_parse(self):
        event = CrashEvent.parse("a1:30")
        assert event == CrashEvent("a1", 30)
        with pytest.raises(ValueError):
            CrashEvent.parse("30")

    def test_silent_kill_stops_thread_without_report(self):
        """kill_agent(report=False): the victim's thread is stopped
        but NO failure report is filed — the mode health-monitored
        chaos runs use, so a death must be *detected*, not announced
        by its own injector (see test_selfheal_battery)."""
        from pydcop_tpu.resilience.faults import kill_agent

        class FakeAgent:
            stopped = False

            def stop(self):
                self.stopped = True

        class FakeOrchestrator:
            def __init__(self):
                self.local_agents = {"a1": FakeAgent()}
                self.reports = []

            def report_agent_failure(self, agent):
                self.reports.append(agent)

        orch = FakeOrchestrator()
        kill_agent(orch, "a1", report=False)
        assert orch.local_agents["a1"].stopped
        assert orch.reports == []
        orch2 = FakeOrchestrator()
        kill_agent(orch2, "a1")  # default: report as before
        assert orch2.reports == ["a1"]


# ------------------------------------------------------------------ #
# Checkpoint / resume


class TestCheckpoint:
    def _engine(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        return build_engine(_ring_dcop(), {})

    def test_state_roundtrip(self, tmp_path):
        import numpy as np

        engine = self._engine()
        state = engine.init_state()
        path = str(tmp_path / "s.npz")
        save_state(path, state, cycle=0, extra={"tag": "t"})
        loaded, meta = load_state(path, engine.init_state())
        assert meta["cycle"] == 0
        assert meta["extra"] == {"tag": "t"}
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(loaded)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_manager_prunes_and_finds_latest(self, tmp_path):
        engine = self._engine()
        manager = CheckpointManager(str(tmp_path), every=5, keep=2)
        state = engine.init_state()
        for cycle in (5, 10, 15):
            manager.save(state, cycle)
        cycles = [c for c, _ in manager.checkpoints()]
        assert cycles == [10, 15]  # keep=2 pruned cycle 5
        assert manager.latest().endswith("ckpt_15.npz")

    def test_latest_skips_corrupt_snapshot(self, tmp_path):
        engine = self._engine()
        manager = CheckpointManager(str(tmp_path), every=5, keep=3)
        manager.save(engine.init_state(), 5)
        with open(manager.path_for(99), "wb") as f:
            f.write(b"not an npz")
        assert manager.latest().endswith("ckpt_5.npz")

    def test_interrupt_resume_matches_uninterrupted(self, tmp_path):
        """THE determinism criterion: interrupted at an arbitrary
        segment boundary + resumed == uninterrupted, in assignment,
        cost and cycle count."""
        dcop = _ring_dcop()
        reference = self._engine().run(max_cycles=100)
        assert reference.cycles > 5  # interrupt lands mid-run

        engine = self._engine()
        manager = CheckpointManager(str(tmp_path), every=5, keep=2)
        partial = engine.run_checkpointed(
            max_cycles=100, manager=manager, max_segments=1
        )
        assert partial.metrics["interrupted"]
        assert partial.cycles == 5
        assert manager.latest().endswith("ckpt_5.npz")

        # "New process": a fresh engine restores the snapshot.
        from pydcop_tpu.algorithms.maxsum import build_engine

        engine2 = build_engine(dcop, {})
        resumed = resume_from_checkpoint(
            engine2, manager, max_cycles=100)
        assert resumed.metrics["resumed_from_cycle"] == 5
        assert resumed.cycles == reference.cycles
        assert resumed.converged == reference.converged
        assert resumed.assignment == reference.assignment
        ref_cost, _ = dcop.solution_cost(reference.assignment)
        res_cost, _ = dcop.solution_cost(resumed.assignment)
        assert res_cost == ref_cost

    def test_segmented_run_matches_single_program(self):
        reference = self._engine().run(max_cycles=100)
        segmented = self._engine().run_checkpointed(
            max_cycles=100, segment_cycles=7)
        assert segmented.cycles == reference.cycles
        assert segmented.assignment == reference.assignment

    def test_resume_without_snapshot_starts_fresh(self, tmp_path):
        engine = self._engine()
        result = resume_from_checkpoint(
            engine, str(tmp_path), max_cycles=100)
        assert result.metrics["resumed_from_cycle"] == 0
        assert result.cycles == self._engine().run(max_cycles=100).cycles

    def test_async_interrupt_resume_matches_uninterrupted(
            self, tmp_path):
        """The determinism criterion under the ASYNC writer + donated
        buffers (both defaults): interrupt at a segment boundary,
        resume from the background-written snapshot, equal the
        uninterrupted run exactly."""
        reference = self._engine().run(max_cycles=100)
        manager = CheckpointManager(str(tmp_path), every=5, keep=2)
        partial = self._engine().run_checkpointed(
            max_cycles=100, manager=manager, max_segments=1,
            checkpoint_async=True,
        )
        assert partial.metrics["checkpoint_async"]
        # Flushed before return: the snapshot is already readable.
        assert manager.latest().endswith("ckpt_5.npz")
        resumed = resume_from_checkpoint(
            self._engine(), manager, max_cycles=100,
            checkpoint_async=True,
        )
        assert resumed.metrics["resumed_from_cycle"] == 5
        assert resumed.cycles == reference.cycles
        assert resumed.assignment == reference.assignment

    def test_donation_off_matches_default(self):
        """donate=False (state buffers kept) and donate=True (buffers
        reused in place) must walk the same trajectory."""
        ref = self._engine().run_checkpointed(
            max_cycles=100, segment_cycles=7)
        engine = self._engine()
        engine.donate = False
        undonated = engine.run_checkpointed(
            max_cycles=100, segment_cycles=7)
        assert undonated.assignment == ref.assignment
        assert undonated.cycles == ref.cycles
        assert undonated.converged == ref.converged

    def test_api_solve_checkpointed(self, tmp_path):
        from pydcop_tpu.api import solve

        dcop = _ring_dcop()
        ref = solve(dcop, "maxsum", backend="device", max_cycles=100)
        res = solve(
            dcop, "maxsum", backend="device", max_cycles=100,
            checkpoint_dir=str(tmp_path), checkpoint_every=10,
        )
        assert res["cost"] == ref["cost"]
        assert res["cycles"] == ref["cycles"]
        assert (tmp_path / f"ckpt_{res['cycles']}.npz").exists()
        # And resume from the finished state reproduces the result.
        res2 = solve(
            dcop, "maxsum", backend="device", max_cycles=100,
            checkpoint_dir=str(tmp_path), checkpoint_every=10,
            resume=True,
        )
        assert res2["assignment"] == res["assignment"]


# ------------------------------------------------------------------ #
# Chaos battery: solves under injected faults


class TestChaosConvergence:
    def test_amaxsum_under_drop_dup_delay(self):
        """Async MaxSum under seeded 10% drop + dup + delay reaches
        the fault-free cost (0 on the 3-colorable chain)."""
        from pydcop_tpu.infrastructure.run import solve_with_agents

        dist = Distribution({
            "a0": ["v0", "diff_0_1"], "a1": ["v1"],
            "a2": ["v2", "diff_1_2"], "a3": ["v3", "diff_2_3"],
            "a4": [],
        })
        plan = FaultPlan(seed=CHAOS_SEED, drop=0.10, duplicate=0.05,
                         delay=0.05, delay_time=0.02)
        res = solve_with_agents(
            _coloring_dcop(), "amaxsum", distribution=dist,
            timeout=6, fault_plan=plan,
        )
        assert res["cost"] == 0
        stats = res["fault_stats"]
        assert stats["dropped"] > 0, (
            "chaos run injected no faults — not a chaos run")

    def test_dsa_under_dup_delay(self):
        """Synchronous DSA tolerates duplication and delay (cycle
        alignment shifts but progresses) and reaches cost 0.  Drop is
        excluded by design: cycle-synchronous algorithms deadlock on
        loss — that is what the async variants are for."""
        from pydcop_tpu.infrastructure.run import solve_with_agents

        algo = AlgorithmDef.build_with_default_param(
            "dsa", {"stop_cycle": 100}, mode="min")
        plan = FaultPlan(seed=CHAOS_SEED, duplicate=0.10, delay=0.10,
                         delay_time=0.02)
        res = solve_with_agents(
            _coloring_dcop(), algo,
            distribution=_variable_distribution(),
            timeout=6, fault_plan=plan,
        )
        assert res["cost"] == 0
        assert res["fault_stats"]["duplicated"] > 0

    def test_kill_and_repair_mid_solve(self):
        """Murder one agent mid-solve under 10% drop: the replication
        + reparation path migrates its computation and the orchestrated
        solve COMPLETES at the fault-free cost."""
        from pydcop_tpu.infrastructure.run import solve_with_agents

        algo = AlgorithmDef.build_with_default_param(
            "adsa", {"stop_cycle": 40, "period": 0.05}, mode="min")
        plan = FaultPlan(
            seed=CHAOS_SEED, drop=0.10,
            crashes=(CrashEvent("a1", 5),), replicas=2,
        )
        res = solve_with_agents(
            _coloring_dcop(), algo,
            distribution=_variable_distribution(),
            timeout=45, fault_plan=plan,
        )
        assert res["killed_agents"] == ["a1"]
        assert res["status"] == "FINISHED"
        assert res["cost"] == 0
        # Every variable still has a value: v1 was re-hosted, not lost.
        assert set(res["assignment"]) == {"v0", "v1", "v2", "v3"}


# ------------------------------------------------------------------ #
# Transport hardening


class TestHttpDeadAgentMark:
    def test_refused_connection_marks_agent_dead(self):
        """Acceptance: send_msg to a refused connection retries per
        RetryPolicy, never raises through the caller, and ends in a
        Discovery dead-agent mark."""
        from pydcop_tpu.infrastructure.communication import (
            HttpCommunicationLayer,
        )

        class Disco:
            def __init__(self):
                self.addresses = {}
                self.unregistered = []

            def agent_address(self, name):
                return self.addresses[name]

            def unregister_agent(self, name):
                self.unregistered.append(name)

        disco = Disco()
        layer = HttpCommunicationLayer(
            ("127.0.0.1", next(PORTS)),
            retry_policy=RetryPolicy(
                max_attempts=None, base_delay=0.05, max_delay=0.2,
                jitter=0.0,
            ),
        )
        try:
            layer.discovery = disco
            layer.RETRY_WINDOW = 0.6
            layer.RETRY_INTERVAL = 0.05
            disco.addresses["dead"] = ("127.0.0.1", 1)  # refused
            layer.send_msg("me", "dead", _msg())  # must not raise
            deadline = time.monotonic() + 10
            while not disco.unregistered and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert disco.unregistered == ["dead"]
            assert not layer._retry_queue
            # The mark fed back through on_agent_change: new sends to
            # the dead agent are dropped immediately, without retries.
            layer.on_agent_change("agent_removed", "dead")
            layer.send_msg("me", "dead", _msg())
            assert not layer._retry_queue
        finally:
            layer.shutdown()

    def test_breaker_skips_attempts_to_failing_destination(self):
        from pydcop_tpu.infrastructure.communication import (
            HttpCommunicationLayer,
        )

        class Disco:
            def agent_address(self, name):
                return ("127.0.0.1", 1)

        layer = HttpCommunicationLayer(("127.0.0.1", next(PORTS)))
        try:
            layer.discovery = Disco()
            layer._breaker_threshold = 2
            for _ in range(3):
                error = layer._try_send("me", "dead", _msg())
                assert error is not None
            assert "circuit open" in layer._try_send(
                "me", "dead", _msg())
        finally:
            layer.shutdown()


class TestMessagingShutdownContract:
    def test_shutdown_wakes_blocked_next_msg(self):
        comm = InProcessCommunicationLayer()
        messaging = Messaging("a", comm)
        result = {}

        def blocked_pop():
            t0 = time.monotonic()
            result["msg"] = messaging.next_msg(timeout=10)
            result["elapsed"] = time.monotonic() - t0

        thread = threading.Thread(target=blocked_pop, daemon=True)
        thread.start()
        time.sleep(0.2)
        messaging.shutdown()
        thread.join(3)
        assert not thread.is_alive(), "next_msg waited past shutdown"
        assert result["msg"] is None
        assert result["elapsed"] < 5, "woke by timeout, not shutdown"

    def test_queued_messages_drain_after_shutdown(self):
        comm = InProcessCommunicationLayer()
        messaging = Messaging("a", comm)
        messaging.post_local(_msg(prio=MSG_ALGO, content="algo"))
        messaging.post_local(_msg(prio=MSG_MGT, content="mgt"))
        messaging.shutdown()
        # No message silently dropped: both drain, priority order
        # preserved, and the empty queue answers None WITHOUT waiting.
        assert messaging.next_msg(timeout=10).msg.content == "mgt"
        assert messaging.next_msg(timeout=10).msg.content == "algo"
        t0 = time.monotonic()
        assert messaging.next_msg(timeout=10) is None
        assert time.monotonic() - t0 < 1

    def test_send_to_dead_inprocess_agent_never_raises(self):
        """_send_remote retries then drops + logs — an unreachable
        peer must not kill the calling agent thread."""
        comm = InProcessCommunicationLayer()
        messaging = Messaging(
            "a", comm,
            retry_policy=RetryPolicy(max_attempts=2, base_delay=0.001,
                                     jitter=0.0),
        )
        from pydcop_tpu.infrastructure.discovery import Discovery

        disco = Discovery("a", comm)
        comm.discovery = disco
        # Destination agent registered but its address is bogus (the
        # in-process address protocol needs a layer object).
        disco.register_agent("ghost", object(), publish=False)
        messaging._send_remote("ghost", _msg())  # must not raise
        # The known-but-unreachable agent was marked dead locally.
        assert "ghost" not in disco.agents()


class TestMultihostJoinRetry:
    @pytest.fixture()
    def multihost(self):
        from pydcop_tpu.engine import multihost as mh

        was_initialized = mh._initialized
        mh._reset_initialized()
        yield mh
        mh._initialized = was_initialized

    def test_join_retries_until_coordinator_up(self, multihost,
                                               monkeypatch):
        import jax

        calls = []

        def flaky_initialize(**kwargs):
            calls.append(kwargs)
            if len(calls) < 3:
                raise RuntimeError("UNAVAILABLE: connection refused")

        monkeypatch.setattr(
            jax.distributed, "initialize", flaky_initialize)
        multihost.initialize_multihost(
            coordinator_address="127.0.0.1:65500",
            num_processes=1, process_id=0,
            retry_policy=RetryPolicy(max_attempts=5, base_delay=0.01,
                                     jitter=0.0),
        )
        assert len(calls) == 3
        assert multihost.multihost_initialized()

    def test_failed_join_keeps_state_unlatched(self, multihost,
                                               monkeypatch):
        import jax

        def dead_initialize(**kwargs):
            raise RuntimeError("UNAVAILABLE: connection refused")

        monkeypatch.setattr(
            jax.distributed, "initialize", dead_initialize)
        with pytest.raises(RetryExhaustedError):
            multihost.initialize_multihost(
                coordinator_address="127.0.0.1:65500",
                num_processes=1, process_id=0,
                retry_policy=RetryPolicy(max_attempts=2,
                                         base_delay=0.01, jitter=0.0),
            )
        assert not multihost.multihost_initialized()
        # A later attempt (coordinator now up) succeeds: the failure
        # did not latch module state.
        monkeypatch.setattr(
            jax.distributed, "initialize", lambda **kw: None)
        multihost.initialize_multihost(
            coordinator_address="127.0.0.1:65500",
            num_processes=1, process_id=0,
            retry_policy=RetryPolicy(max_attempts=1),
        )
        assert multihost.multihost_initialized()

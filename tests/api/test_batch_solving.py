"""Batched multi-instance device solving tests: one vmapped XLA
program must produce bit-identical results to solving each instance
separately, and reject shape-mismatched batches."""

import time

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.batch import solve_maxsum_batch
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.engine.runner import MaxSumEngine


def _instance(n: int, seed: int, objective: str = "min") -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"b{n}_{seed}_{objective}", objective=objective)
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    # Same topology across seeds (ring + fixed chords), different
    # random cost tables: identical compiled shapes.
    edges = [(i, (i + 1) % n) for i in range(n)]
    edges += [(i, (i + n // 2) % n) for i in range(0, n, 3)]
    for k, (i, j) in enumerate(edges):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def test_batch_matches_individual_solves():
    dcops = [_instance(24, seed) for seed in range(6)]
    batch = solve_maxsum_batch(dcops, max_cycles=80)
    for dcop, res in zip(dcops, batch):
        graph, meta = compile_dcop(dcop, noise_level=0.01)
        solo = MaxSumEngine(graph, meta).run(
            max_cycles=80, stop_on_convergence=False)
        assert res["assignment"] == solo.assignment
        assert res["cycles"] == 80


def test_batch_rejects_shape_mismatch():
    a = _instance(24, 0)
    b = _instance(30, 1)
    with pytest.raises(ValueError, match="identical compiled shapes"):
        solve_maxsum_batch([a, b])


def test_batch_amortizes_launch_overhead():
    """The whole batch runs in one program: wall time for 8 instances
    is far less than 8x one instance's (compile excluded for both)."""
    dcops = [_instance(40, seed) for seed in range(8)]
    solve_maxsum_batch(dcops, max_cycles=60)  # warm the jit cache
    t0 = time.perf_counter()
    solve_maxsum_batch(dcops, max_cycles=60)
    batched = time.perf_counter() - t0

    graph, meta = compile_dcop(dcops[0], noise_level=0.01)
    engine = MaxSumEngine(graph, meta)
    engine.run(max_cycles=60, stop_on_convergence=False)  # warm
    t0 = time.perf_counter()
    for dcop in dcops:
        g, m = compile_dcop(dcop, noise_level=0.01)
        MaxSumEngine(g, m).run(
            max_cycles=60, stop_on_convergence=False)
    sequential = time.perf_counter() - t0
    # Sequential pays per-instance re-jit + launch; batched pays one.
    assert batched < sequential

def test_batch_handles_max_objective():
    """objective=max problems negate at compile time; the batched path
    must decode the maximizing assignment — checked against an
    independent host-side evaluation, not the engine's own cost."""
    dcops = [_instance(12, seed, objective="max") for seed in range(3)]
    batch = solve_maxsum_batch(dcops, max_cycles=80)
    rng = np.random.default_rng(99)
    for dcop, res in zip(dcops, batch):
        # Same assignment as the solo engine (sign handling agrees).
        graph, meta = compile_dcop(dcop, noise_level=0.01)
        solo = MaxSumEngine(graph, meta).run(
            max_cycles=80, stop_on_convergence=False)
        assert res["assignment"] == solo.assignment
        # Independent check: the reported cost is the raw table sum of
        # the assignment (not accidentally negated)...
        raw = sum(
            float(c(*(res["assignment"][v.name]
                      for v in c.dimensions)))
            for c in dcop.constraints.values()
        )
        assert res["cost"] == raw
        # ...and the solver actually MAXIMIZED: it beats random
        # assignments comfortably.
        rand = {
            v: int(rng.integers(0, 3)) for v in dcop.variables
        }
        rand_cost, _ = dcop.solution_cost(rand)
        assert res["cost"] > rand_cost

"""Agent-mode computations for the breakout / local-search family:
DBA, GDBA, MixedDSA and MGM2.

Reference parity (semantics, not translation):
- dba: pydcop/algorithms/dba.py:272-595 — ok/improve waves, per-agent
  constraint weights bumped at quasi-local minima, termination via
  distance counters.
- gdba: pydcop/algorithms/gdba.py:189-654 — generalized breakout on
  optimization problems with modifier tables (A/M), violation tests
  (NZ/NM/MX) and increase scopes (E/R/C/T).
- mixeddsa: pydcop/algorithms/mixeddsa.py:154-470 — DSA distinguishing
  hard (infinite-cost) from soft constraints, with proba_hard /
  proba_soft move probabilities.
- mgm2: pydcop/algorithms/mgm2.py:399-1050 — 5-phase coordinated
  2-opt: value / offer / response / gain / go.

The device kernels for the same algorithms live in pydcop_tpu/ops/
(dba.py, gdba.py, mixeddsa.py, mgm2.py); these message-passing
versions mirror their decision rules so thread-mode and device-mode
runs explore comparable search spaces.
"""

import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pydcop_tpu.infrastructure.agent_common import (
    HypergraphComputation as _HypergraphComputation,
    scan_best,
    wins_neighborhood,
)
from pydcop_tpu.infrastructure.computations import (
    message_type,
    register,
)

# -- shared helpers ----------------------------------------------------- #


def _constraint_cost(constraint, assignment: Dict[str, Any]) -> float:
    return constraint(
        **{n: assignment[n] for n in constraint.scope_names}
    )


# -- DBA ---------------------------------------------------------------- #

DbaOkMessage = message_type("dba_ok", ["value"])
DbaImproveMessage = message_type(
    "dba_improve", ["improve", "eval", "termination_counter"])
DbaEndMessage = message_type("dba_end", [])


class DbaComputation(_HypergraphComputation):
    """Distributed Breakout: ok-phase / improve-phase waves.

    Violation = constraint cost >= ``infinity``; eval(value) = weighted
    count of violated incident constraints with neighbors at their last
    announced values; each agent keeps its own weight per incident
    constraint, bumped by 1 at quasi-local minima (reference
    dba.py:452, :563-565; device twin ops/dba.py).
    """

    def __init__(self, comp_def):
        super().__init__(comp_def)
        params = comp_def.algo.params
        self.infinity = params.get("infinity", 10000)
        self.max_distance = params.get("max_distance", 50)
        self.stop_cycle = params.get("stop_cycle", 0)
        self._weights = {c.name: 1.0 for c in self.constraints}
        self._term_counter = 0.0
        self._state = "ok"
        self._neighbor_values: Dict[str, Any] = {}
        self._neighbor_improves: Dict[str, Tuple[float, float, float]] = {}
        self._postponed_ok: List[Tuple] = []
        self._postponed_improve: List[Tuple] = []
        self._improve = 0.0
        self._proposed = None
        self._ended = False

    def on_start(self):
        if self._finish_no_neighbors():
            return
        self.random_value_selection()
        self.post_to_all_neighbors(DbaOkMessage(self.current_value))

    def _eval(self, value) -> float:
        asst = dict(self._neighbor_values)
        asst[self.name] = value
        total = 0.0
        for c in self.constraints:
            if _constraint_cost(c, asst) >= self.infinity:
                total += self._weights[c.name]
        return total

    @register("dba_ok")
    def _on_ok(self, sender, msg, t):
        if self._ended:
            return
        if self._state == "ok":
            self._handle_ok(sender, msg.value)
        else:
            self._postponed_ok.append((sender, msg.value))

    def _handle_ok(self, sender, value):
        self._neighbor_values[sender] = value
        if len(self._neighbor_values) < len(self._neighbors):
            return
        cur_eval = self._eval(self.current_value)
        best_eval, best_vals = scan_best(
            self._variable.domain, self._eval
        )
        self._improve = cur_eval - best_eval
        self._cur_eval = cur_eval
        self._proposed = random.choice(best_vals)
        if cur_eval != 0:
            self._term_counter = 0.0
        self._state = "improve"
        self.post_to_all_neighbors(DbaImproveMessage(
            self._improve, cur_eval, self._term_counter
        ))
        for s, m in self._postponed_improve:
            self._handle_improve(s, m)
        self._postponed_improve.clear()

    @register("dba_improve")
    def _on_improve(self, sender, msg, t):
        if self._ended:
            return
        if self._state == "improve":
            self._handle_improve(sender, msg)
        else:
            self._postponed_improve.append((sender, msg))

    def _handle_improve(self, sender, msg):
        self._neighbor_improves[sender] = (
            msg.improve, msg.eval, msg.termination_counter
        )
        if len(self._neighbor_improves) < len(self._neighbors):
            return
        n_improves = {
            s: i for s, (i, _, _) in self._neighbor_improves.items()
        }
        n_max = max(n_improves.values())
        wins = wins_neighborhood(self.name, self._improve, n_improves)
        if self._improve > 0 and wins:
            self.value_selection(
                self._proposed, self._cur_eval - self._improve
            )
        # Quasi-local minimum: nobody can improve -> breakout.
        if self._improve <= 0 and n_max <= 0:
            asst = dict(self._neighbor_values)
            asst[self.name] = self.current_value
            for c in self.constraints:
                if _constraint_cost(c, asst) >= self.infinity:
                    self._weights[c.name] += 1.0
        # Termination counters (dba.py:405,:509,:541).
        n_tc_min = min(
            tc for _, _, tc in self._neighbor_improves.values()
        )
        self._term_counter = min(self._term_counter, n_tc_min)
        consistent = self._cur_eval == 0 and all(
            e == 0 for _, e, _ in self._neighbor_improves.values()
        )
        if consistent:
            self._term_counter += 1
        self._neighbor_values.clear()
        self._neighbor_improves.clear()
        self._state = "ok"
        self.new_cycle()
        if self._term_counter >= self.max_distance or (
            self.stop_cycle and self.cycle_count >= self.stop_cycle
        ):
            self._end()
            return
        self.post_to_all_neighbors(DbaOkMessage(self.current_value))
        for s, v in self._postponed_ok:
            self._handle_ok(s, v)
        self._postponed_ok.clear()

    def _end(self):
        if self._ended:
            return
        self._ended = True
        self.post_to_all_neighbors(DbaEndMessage())
        self.finished()

    @register("dba_end")
    def _on_end(self, sender, msg, t):
        self._end()


# -- GDBA --------------------------------------------------------------- #

GdbaOkMessage = message_type("gdba_ok", ["value"])
GdbaImproveMessage = message_type("gdba_improve", ["improve"])


class GdbaComputation(_HypergraphComputation):
    """Generalized Distributed Breakout (optimization problems).

    Each agent keeps a modifier table per incident constraint (same
    shape as its cost hypercube); effective cost = base + modifier
    (mode A) or base * modifier (mode M).  At neighborhood minima the
    modifiers of *violated* constraints increase on entries selected by
    ``increase_mode`` (reference gdba.py:552-654; device twin
    ops/gdba.py).
    """

    def __init__(self, comp_def):
        super().__init__(comp_def)
        params = comp_def.algo.params
        self.modifier_mode = params.get("modifier", "A")
        self.violation_mode = params.get("violation", "NZ")
        self.increase_mode = params.get("increase_mode", "E")
        self.stop_cycle = params.get("stop_cycle", 0)
        base = 0.0 if self.modifier_mode == "A" else 1.0
        self._modifiers = {
            c.name: np.full(c.shape, base, dtype=np.float64)
            for c in self.constraints
        }
        self._tables = {
            c.name: self.sign * np.asarray(
                c.to_array(), dtype=np.float64
            )
            for c in self.constraints
        }
        self._minmax = {
            name: (float(t.min()), float(t.max()))
            for name, t in self._tables.items()
        }
        self._state = "ok"
        self._neighbor_values: Dict[str, Any] = {}
        self._neighbor_improves: Dict[str, float] = {}
        self._postponed_ok: List[Tuple] = []
        self._postponed_improve: List[Tuple] = []
        self._improve = 0.0
        self._proposed = None

    def on_start(self):
        if self._finish_no_neighbors():
            return
        self.random_value_selection()
        self.post_to_all_neighbors(GdbaOkMessage(self.current_value))

    def _indices(self, constraint, assignment) -> Tuple[int, ...]:
        return tuple(
            v.domain.index(assignment[v.name])
            for v in constraint.dimensions
        )

    def _eff_cost(self, constraint, assignment) -> float:
        idx = self._indices(constraint, assignment)
        base = self._tables[constraint.name][idx]
        mod = self._modifiers[constraint.name][idx]
        return base + mod if self.modifier_mode == "A" else base * mod

    def _eval(self, value) -> float:
        asst = dict(self._neighbor_values)
        asst[self.name] = value
        total = self.sign * self._variable.cost_for_val(value)
        for c in self.constraints:
            total += self._eff_cost(c, asst)
        return total

    @register("gdba_ok")
    def _on_ok(self, sender, msg, t):
        if self._state == "ok":
            self._handle_ok(sender, msg.value)
        else:
            self._postponed_ok.append((sender, msg.value))

    def _handle_ok(self, sender, value):
        self._neighbor_values[sender] = value
        if len(self._neighbor_values) < len(self._neighbors):
            return
        cur_eval = self._eval(self.current_value)
        best_eval, best_vals = scan_best(
            self._variable.domain, self._eval
        )
        self._improve = cur_eval - best_eval
        self._proposed = random.choice(best_vals)
        self._state = "improve"
        self.post_to_all_neighbors(GdbaImproveMessage(self._improve))
        for s, m in self._postponed_improve:
            self._handle_improve(s, m)
        self._postponed_improve.clear()

    @register("gdba_improve")
    def _on_improve(self, sender, msg, t):
        if self._state == "improve":
            self._handle_improve(sender, msg)
        else:
            self._postponed_improve.append((sender, msg))

    def _handle_improve(self, sender, msg):
        self._neighbor_improves[sender] = msg.improve
        if len(self._neighbor_improves) < len(self._neighbors):
            return
        n_max = max(self._neighbor_improves.values())
        wins = wins_neighborhood(
            self.name, self._improve, self._neighbor_improves
        )
        if self._improve > 0 and wins:
            self.value_selection(self._proposed, 0.0)
        if self._improve <= 0 and n_max <= 0:
            self._increase_modifiers()
        self._neighbor_values.clear()
        self._neighbor_improves.clear()
        self._state = "ok"
        self.new_cycle()
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()
            return
        self.post_to_all_neighbors(GdbaOkMessage(self.current_value))
        for s, v in self._postponed_ok:
            self._handle_ok(s, v)
        self._postponed_ok.clear()

    def _increase_modifiers(self):
        asst = dict(self._neighbor_values)
        asst[self.name] = self.current_value
        for c in self.constraints:
            idx = self._indices(c, asst)
            base = self._tables[c.name][idx]
            fmin, fmax = self._minmax[c.name]
            if self.violation_mode == "NZ":
                violated = base != 0
            elif self.violation_mode == "NM":
                violated = base != fmin
            else:  # MX
                violated = base == fmax
            if not violated:
                continue
            mods = self._modifiers[c.name]
            own_axis = [
                i for i, v in enumerate(c.dimensions)
                if v.name == self.name
            ][0]
            sel: List[Any] = []
            for q in range(len(c.dimensions)):
                if self.increase_mode == "T":
                    sel.append(slice(None))
                elif self.increase_mode == "E":
                    sel.append(idx[q])
                elif self.increase_mode == "R":
                    # Own axis free, others at current.
                    sel.append(
                        slice(None) if q == own_axis else idx[q]
                    )
                else:  # C: own at current, others free
                    sel.append(
                        idx[q] if q == own_axis else slice(None)
                    )
            mods[tuple(sel)] += 1.0


# -- MixedDSA ----------------------------------------------------------- #

MixedDsaMessage = message_type("mixed_dsa_value", ["value"])


class MixedDsaComputation(_HypergraphComputation):
    """DSA over mixed hard (infinite-cost) / soft constraint problems
    (reference mixeddsa.py:154-470; device twin ops/mixeddsa.py).

    Candidates are ranked lexicographically: fewest violated hard
    constraints first, then DCOP cost excluding violated hard
    infinities.  Moves use proba_hard when a hard improvement (or hard
    escape) is available, proba_soft for soft improvements/escapes.
    """

    def __init__(self, comp_def):
        super().__init__(comp_def)
        params = comp_def.algo.params
        self.proba_hard = params.get("proba_hard", 0.7)
        self.proba_soft = params.get("proba_soft", 0.5)
        self.variant = params.get("variant", "B")
        self.stop_cycle = params.get("stop_cycle", 0)
        self._hard = {}
        self._soft_opt = {}
        for c in self.constraints:
            table = self.sign * np.asarray(
                c.to_array(), dtype=np.float64
            )
            is_hard = bool(np.isinf(table).any())
            self._hard[c.name] = is_hard
            if not is_hard:
                self._soft_opt[c.name] = float(table.min())
        self.current_cycle: Dict[str, Any] = {}
        self.next_cycle: Dict[str, Any] = {}

    def on_start(self):
        if self._finish_no_neighbors():
            return
        self.random_value_selection()
        self.post_to_all_neighbors(MixedDsaMessage(self.current_value))

    @register("mixed_dsa_value")
    def _on_value(self, sender, msg, t):
        if not self._running:
            return
        if sender not in self.current_cycle:
            self.current_cycle[sender] = msg.value
            self._evaluate_cycle()
        else:
            self.next_cycle[sender] = msg.value

    def _metrics(self, value) -> Tuple[int, float]:
        """(violated-hard count, cost excluding their infinities)."""
        asst = dict(self.current_cycle)
        asst[self.name] = value
        nb_viol = 0
        cost = self.sign * self._variable.cost_for_val(value)
        for c in self.constraints:
            c_cost = self.sign * _constraint_cost(c, asst)
            if self._hard[c.name] and np.isinf(c_cost):
                nb_viol += 1
            else:
                cost += c_cost
        return nb_viol, cost

    def _soft_violated(self) -> bool:
        asst = dict(self.current_cycle)
        asst[self.name] = self.current_value
        for c in self.constraints:
            if self._hard[c.name]:
                continue
            if self.sign * _constraint_cost(c, asst) != \
                    self._soft_opt[c.name]:
                return True
        return False

    def _evaluate_cycle(self):
        if len(self.current_cycle) < len(self._neighbors):
            return
        cur_nb, cur_cost = self._metrics(self.current_value)
        best: List[Any] = []
        best_nb, best_cost = None, None
        for v in self._variable.domain:
            nb, cost = self._metrics(v)
            key = (nb, cost)
            if best_nb is None or key < (best_nb, best_cost):
                best_nb, best_cost = nb, cost
                best = [v]
            elif key == (best_nb, best_cost):
                best.append(v)
        delta_dcsp = cur_nb - best_nb
        delta_dcop = cur_cost - best_cost
        alt = [v for v in best if v != self.current_value]
        variant_bc = self.variant in ("B", "C")

        proba, pool = 0.0, best
        if delta_dcsp > 0:
            proba = self.proba_hard
        elif delta_dcsp == 0 and delta_dcop > 0:
            proba = self.proba_soft
        elif delta_dcsp == 0 and delta_dcop == 0:
            if best_nb > 0 and alt:
                proba, pool = self.proba_hard, alt
            elif (
                variant_bc and best_nb == 0 and alt
                and self._soft_violated()
            ):
                proba, pool = self.proba_soft, alt
        if proba > 0 and random.random() < proba:
            self.value_selection(random.choice(pool), best_cost)

        self.new_cycle()
        self.current_cycle, self.next_cycle = self.next_cycle, {}
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()
            return
        self.post_to_all_neighbors(MixedDsaMessage(self.current_value))


# -- MGM2 --------------------------------------------------------------- #

Mgm2ValueMessage = message_type("mgm2_value", ["value"])
Mgm2OfferMessage = message_type("mgm2_offer", ["offers"])
Mgm2ResponseMessage = message_type(
    "mgm2_response", ["accept", "my_value", "your_value", "gain"])
Mgm2GainMessage = message_type("mgm2_gain", ["gain"])
Mgm2GoMessage = message_type("mgm2_go", ["go"])


class Mgm2Computation(_HypergraphComputation):
    """MGM2: coordinated 2-opt local search, 5 phases per round
    (reference mgm2.py:399-1050).

    Round structure: every agent broadcasts its value; with probability
    ``threshold`` an agent becomes an *offerer* and proposes joint
    moves to one random neighbor (offers carry the offerer-side gain
    over *all its incident constraints*, the partner adds its own gain
    over its non-shared constraints — no double counting); partners
    accept the best positive offer (``favor`` arbitrates ties against
    the unilateral gain); everyone then broadcasts its committed gain,
    committed pairs exchange go/no-go (move iff the pair's gain beats
    both neighborhoods), unilateral movers follow MGM's strict-winner
    rule.  Global cost is monotone non-increasing: contested ties stay
    put.
    """

    def __init__(self, comp_def):
        super().__init__(comp_def)
        params = comp_def.algo.params
        self.threshold = params.get("threshold", 0.5)
        self.favor = params.get("favor", "unilateral")
        self.stop_cycle = params.get("stop_cycle", 0)
        self._vars_by_name = {
            v.name: v
            for c in self.constraints for v in c.dimensions
        }
        self._phase = "value"
        self._neighbor_values: Dict[str, Any] = {}
        self._offers_in: Dict[str, Any] = {}
        self._gains_in: Dict[str, float] = {}
        self._postponed: Dict[str, List[Tuple]] = {
            "value": [], "offer": [], "response": [], "gain": [],
            "go": [],
        }
        self._is_offerer = False
        self._partner: Optional[str] = None
        self._committed_gain = 0.0
        self._new_value = None
        self._coordinated = False
        self._response_in: Optional[Tuple] = None
        self._go_in: Optional[bool] = None

    def on_start(self):
        if self._finish_no_neighbors():
            return
        self.random_value_selection()
        self.post_to_all_neighbors(Mgm2ValueMessage(self.current_value))

    # -- cost helpers -------------------------------------------------- #

    def _local_cost(self, my_value, overrides: Dict[str, Any] = None,
                    exclude_with: Optional[str] = None) -> float:
        """Sign-normalized cost of incident constraints (+ own unary)
        with neighbors at announced values, optionally overriding some
        and excluding constraints involving ``exclude_with``."""
        asst = dict(self._neighbor_values)
        if overrides:
            asst.update(overrides)
        asst[self.name] = my_value
        total = self.sign * self._variable.cost_for_val(my_value)
        for c in self.constraints:
            if exclude_with is not None and \
                    exclude_with in c.scope_names:
                continue
            total += self.sign * _constraint_cost(c, asst)
        return total

    def _best_unilateral(self) -> Tuple[Any, float]:
        cur = self._local_cost(self.current_value)
        best_v, best_c = self.current_value, cur
        for v in self._variable.domain:
            c = self._local_cost(v)
            if c < best_c:
                best_v, best_c = v, c
        return best_v, cur - best_c

    # -- phase machinery ------------------------------------------------ #

    def _enter(self, phase: str):
        self._phase = phase
        handler = {
            "value": self._handle_value,
            "offer": self._handle_offer,
            "response": self._handle_response,
            "gain": self._handle_gain,
            "go": self._handle_go,
        }[phase]
        postponed, self._postponed[phase] = self._postponed[phase], []
        for args in postponed:
            handler(*args)

    @register("mgm2_value")
    def _on_value(self, sender, msg, t):
        if self._phase == "value":
            self._handle_value(sender, msg.value)
        else:
            self._postponed["value"].append((sender, msg.value))

    def _handle_value(self, sender, value):
        self._neighbor_values[sender] = value
        if len(self._neighbor_values) < len(self._neighbors):
            return
        # All values in: decide role, send offers (real to one random
        # neighbor when offerer, empty to everyone else so the phase
        # completes by counting).
        self._is_offerer = random.random() < self.threshold
        self._partner = None
        self._coordinated = False
        self._response_in = None
        self._go_in = None
        if self._is_offerer:
            self._partner = random.choice(self._neighbors)
            partner_var = self._vars_by_name.get(self._partner)
            offers = []
            cur = self._local_cost(self.current_value)
            for mv in self._variable.domain:
                for pv in partner_var.domain:
                    gain = cur - self._local_cost(
                        mv, overrides={self._partner: pv}
                    )
                    offers.append((mv, pv, gain))
            for n in self._neighbors:
                self.post_msg(
                    n,
                    Mgm2OfferMessage(
                        offers if n == self._partner else []
                    ),
                )
        else:
            for n in self._neighbors:
                self.post_msg(n, Mgm2OfferMessage([]))
        self._enter("offer")

    @register("mgm2_offer")
    def _on_offer(self, sender, msg, t):
        if self._phase == "offer":
            self._handle_offer(sender, msg.offers)
        else:
            self._postponed["offer"].append((sender, msg.offers))

    def _handle_offer(self, sender, offers):
        self._offers_in[sender] = offers
        if len(self._offers_in) < len(self._neighbors):
            return
        real_offers = {
            s: o for s, o in self._offers_in.items() if o
        }
        self._offers_in = {}
        uni_value, uni_gain = self._best_unilateral()
        if self._is_offerer or not real_offers:
            # Offerers ignore incoming offers (reject all).
            for s in real_offers:
                self.post_msg(s, Mgm2ResponseMessage(
                    False, None, None, 0.0
                ))
            self._new_value, self._committed_gain = uni_value, uni_gain
            if self._is_offerer:
                self._enter("response")  # await partner's response
            else:
                self._broadcast_gain()
            return
        # Non-offerer with offers: pick the globally best.
        best = None  # (total, offerer, my_new, their_new)
        for offerer, offers_o in real_offers.items():
            cur_excl = self._local_cost(
                self.current_value, exclude_with=offerer
            )
            for their_v, my_v, offerer_gain in offers_o:
                my_gain = cur_excl - self._local_cost(
                    my_v, overrides={offerer: their_v},
                    exclude_with=offerer,
                )
                total = offerer_gain + my_gain
                if best is None or total > best[0]:
                    best = (total, offerer, my_v, their_v)
        accept = best is not None and best[0] > 0 and (
            best[0] > uni_gain
            if self.favor != "coordinated" else best[0] >= uni_gain
        )
        for s in real_offers:
            if accept and s == best[1]:
                self.post_msg(s, Mgm2ResponseMessage(
                    True, best[3], best[2], best[0]
                ))
            else:
                self.post_msg(s, Mgm2ResponseMessage(
                    False, None, None, 0.0
                ))
        if accept:
            self._partner = best[1]
            self._coordinated = True
            self._new_value = best[2]
            self._committed_gain = best[0]
        else:
            self._new_value, self._committed_gain = uni_value, uni_gain
        self._broadcast_gain()

    @register("mgm2_response")
    def _on_response(self, sender, msg, t):
        if self._phase == "response":
            self._handle_response(sender, msg)
        else:
            self._postponed["response"].append((sender, msg))

    def _handle_response(self, sender, msg):
        if sender != self._partner:
            return  # stale reject from an earlier round
        self._response_in = msg
        if msg.accept:
            self._coordinated = True
            self._new_value = msg.my_value
            self._committed_gain = msg.gain
        self._broadcast_gain()

    def _broadcast_gain(self):
        self.post_to_all_neighbors(
            Mgm2GainMessage(self._committed_gain)
        )
        self._enter("gain")

    @register("mgm2_gain")
    def _on_gain(self, sender, msg, t):
        if self._phase == "gain":
            self._handle_gain(sender, msg.gain)
        else:
            self._postponed["gain"].append((sender, msg.gain))

    def _handle_gain(self, sender, gain):
        self._gains_in[sender] = gain
        if len(self._gains_in) < len(self._neighbors):
            return
        others = {
            s: g for s, g in self._gains_in.items()
            if not (self._coordinated and s == self._partner)
        }
        n_max = max(others.values()) if others else float("-inf")
        if self._coordinated:
            # Pair moves only on a strict win in both neighborhoods:
            # an equal-gain contender might move simultaneously.
            ok = (
                self._committed_gain > 0
                and self._committed_gain > n_max
            )
        else:
            # Unilateral movers follow MGM's rule: strict win, or tie
            # broken by lexically-smallest name (guarantees progress
            # when gains are symmetric).
            ok = self._committed_gain > 0 and wins_neighborhood(
                self.name, self._committed_gain, others
            )
        self._gains_in = {}
        if self._coordinated:
            self.post_msg(self._partner, Mgm2GoMessage(ok))
            self._my_go = ok
            self._enter("go")
        else:
            if ok:
                self.value_selection(self._new_value, 0.0)
            self._next_round()

    @register("mgm2_go")
    def _on_go(self, sender, msg, t):
        if self._phase == "go":
            self._handle_go(sender, msg.go)
        else:
            self._postponed["go"].append((sender, msg.go))

    def _handle_go(self, sender, go):
        if sender != self._partner:
            return
        if go and self._my_go:
            self.value_selection(self._new_value, 0.0)
        self._next_round()

    def _next_round(self):
        self._neighbor_values.clear()
        self.new_cycle()
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()
            return
        self.post_to_all_neighbors(Mgm2ValueMessage(self.current_value))
        self._enter("value")

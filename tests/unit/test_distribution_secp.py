"""SECP distribution family tests.

Verify that the SECP-specialized methods reproduce the reference's
placement rules (reference gh_secp_cgdp.py:75-124, gh_secp_fgdp.py:
92-198, oilp_secp_fgdp.py:72-131, oilp_cgdp.py:174-185) on problems
from our own SECP generator:

- actuator variables (hosting cost 0) are pinned on their agent;
- factor-graph flavors co-locate ``c_<actuator>`` cost factors and
  (model variable, ``c_<model>`` factor) pairs;
- greedy placements put every non-pinned computation next to at least
  one neighbor; ILP placements are never worse than the greedy ones on
  the comm-only objective;
- capacities hold and every computation is hosted exactly once.
"""

import pytest

from pydcop_tpu.algorithms import load_algorithm_module
from pydcop_tpu.computations_graph import load_graph_module
from pydcop_tpu.distribution import (
    gh_secp_cgdp,
    gh_secp_fgdp,
    oilp_cgdp,
    oilp_secp_cgdp,
    oilp_secp_fgdp,
)
from pydcop_tpu.generators.secp import generate_secp

LIGHTS, MODELS, RULES = 5, 2, 3


@pytest.fixture(scope="module")
def secp():
    return generate_secp(
        LIGHTS, MODELS, RULES, capacity=10_000, seed=11)


def _graph(dcop, algo):
    module = load_algorithm_module(algo)
    cg = load_graph_module(module.GRAPH_TYPE).build_computation_graph(
        dcop)
    return cg, module


def _check_common(dist, cg, agents):
    hosted = sorted(dist.computations)
    assert hosted == sorted(n.name for n in cg.nodes)
    by_agent = {a.name: dist.computations_hosted(a.name) for a in agents}
    for a in agents:
        for c in by_agent[a.name]:
            assert dist.agent_for(c) == a.name
        assert len(by_agent[a.name]) == len(set(by_agent[a.name]))


def _check_actuators_pinned(dist, dcop):
    for i in range(LIGHTS):
        assert dist.agent_for(f"l{i}") == f"a{i}"


class TestGhSecpFgdp:
    def test_placement_rules(self, secp):
        cg, module = _graph(secp, "maxsum")
        dist = gh_secp_fgdp.distribute(
            cg, secp.agents.values(),
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        _check_common(dist, cg, list(secp.agents.values()))
        _check_actuators_pinned(dist, secp)
        # Cost factors ride with their actuator.
        for i in range(LIGHTS):
            assert dist.agent_for(f"c_l{i}") == f"a{i}"
        # Model variable and model factor are co-located.
        for j in range(MODELS):
            assert (dist.agent_for(f"m{j}")
                    == dist.agent_for(f"c_m{j}"))
        # Every rule factor lives with at least one neighbor.
        for k in range(RULES):
            name = f"r_{k}"
            agent = dist.agent_for(name)
            neighbors = cg.computation(name).neighbors
            hosted = set(dist.computations_hosted(agent))
            assert hosted.intersection(neighbors)

    def test_requires_computation_memory(self, secp):
        cg, _ = _graph(secp, "maxsum")
        from pydcop_tpu.distribution.objects import (
            ImpossibleDistributionException,
        )

        with pytest.raises(ImpossibleDistributionException):
            gh_secp_fgdp.distribute(cg, secp.agents.values())


class TestGhSecpCgdp:
    def test_placement_rules(self, secp):
        cg, module = _graph(secp, "dsa")
        dist = gh_secp_cgdp.distribute(
            cg, secp.agents.values(),
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        _check_common(dist, cg, list(secp.agents.values()))
        _check_actuators_pinned(dist, secp)
        # Model variables live next to at least one neighbor.
        for j in range(MODELS):
            name = f"m{j}"
            agent = dist.agent_for(name)
            neighbors = cg.computation(name).neighbors
            hosted = set(dist.computations_hosted(agent))
            assert hosted.intersection(neighbors)


class TestOilpSecp:
    def test_cgdp_optimal_vs_greedy(self, secp):
        cg, module = _graph(secp, "dsa")
        kwargs = dict(
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        greedy = gh_secp_cgdp.distribute(
            cg, secp.agents.values(), **kwargs)
        optimal = oilp_secp_cgdp.distribute(
            cg, secp.agents.values(), **kwargs)
        _check_common(optimal, cg, list(secp.agents.values()))
        _check_actuators_pinned(optimal, secp)
        # Every agent hosts at least one computation.
        for a in secp.agents:
            assert optimal.computations_hosted(a)
        g_cost, _, _ = oilp_secp_cgdp.distribution_cost(
            greedy, cg, secp.agents.values(), **kwargs)
        o_cost, _, _ = oilp_secp_cgdp.distribution_cost(
            optimal, cg, secp.agents.values(), **kwargs)
        assert o_cost <= g_cost + 1e-9

    def test_fgdp_optimal_vs_greedy(self, secp):
        cg, module = _graph(secp, "maxsum")
        kwargs = dict(
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        greedy = gh_secp_fgdp.distribute(
            cg, secp.agents.values(), **kwargs)
        optimal = oilp_secp_fgdp.distribute(
            cg, secp.agents.values(), **kwargs)
        _check_common(optimal, cg, list(secp.agents.values()))
        _check_actuators_pinned(optimal, secp)
        # Actuator cost factors stay with their agent (pinned pre-ILP).
        for i in range(LIGHTS):
            assert optimal.agent_for(f"c_l{i}") == f"a{i}"
        g_cost, _, _ = oilp_secp_fgdp.distribution_cost(
            greedy, cg, secp.agents.values(), **kwargs)
        o_cost, _, _ = oilp_secp_fgdp.distribution_cost(
            optimal, cg, secp.agents.values(), **kwargs)
        assert o_cost <= g_cost + 1e-9

    def test_comm_only_cost_model(self, secp):
        """SECP distribution cost = communication only: co-located
        ends contribute nothing, hosting is always 0."""
        cg, module = _graph(secp, "maxsum")
        dist = gh_secp_fgdp.distribute(
            cg, secp.agents.values(),
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        total, comm, hosting = oilp_secp_fgdp.distribution_cost(
            dist, cg, secp.agents.values(),
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        assert hosting == 0.0
        assert total == comm >= 0.0


class TestOilpCgdp:
    def test_pins_zero_hosting_cost(self, secp):
        cg, module = _graph(secp, "dsa")
        dist = oilp_cgdp.distribute(
            cg, secp.agents.values(),
            computation_memory=module.computation_memory,
            communication_load=module.communication_load,
        )
        _check_common(dist, cg, list(secp.agents.values()))
        # Reference oilp_cgdp.py:174-185: zero-hosting-cost computations
        # are forced onto their agent.
        _check_actuators_pinned(dist, secp)

"""gh_cgdp: greedy heuristic for the Constraint-Graph Distribution
Problem.

Reference parity: pydcop/distribution/gh_cgdp.py (:69): highest-degree
computations first, cheapest (comm + hosting) feasible agent.
"""

from pydcop_tpu.distribution._base import (
    RATIO_HOST_COMM,
    distribution_cost_impl,
    greedy_place,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None, **_):
    return greedy_place(
        computation_graph, agentsdef, hints,
        computation_memory, communication_load,
        order_key=lambda c, fp, nb: -len(nb.get(c, [])),
        comm_weight=RATIO_HOST_COMM,
        hosting_weight=1 - RATIO_HOST_COMM,
    )


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return distribution_cost_impl(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load)

"""Entry-point helpers for agent-mode runs.

Reference parity: pydcop/infrastructure/run.py (solve :52,
run_local_thread_dcop :145, run_local_process_dcop :225).
"""

import importlib
import logging
from typing import Dict, Optional

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.computations_graph import load_graph_module
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.infrastructure.communication import (
    InProcessCommunicationLayer,
)
from pydcop_tpu.infrastructure.orchestratedagents import (
    ORCHESTRATOR_AGENT,
    OrchestratedAgent,
)
from pydcop_tpu.infrastructure.orchestrator import Orchestrator

logger = logging.getLogger("pydcop.run")


# Readiness window for agents that live in spawned OS processes: the
# child pays interpreter start + package import before it can register.
# Thread-mode agents register in milliseconds; 10 s is generous there.
PROCESS_READY_TIMEOUT = 30.0
THREAD_READY_TIMEOUT = 10.0


def _build_distribution(dcop: DCOP, cg, algo_module,
                        distribution: str) -> Distribution:
    if distribution.endswith((".yaml", ".yml")):
        from pydcop_tpu.dcop.yamldcop import load_dist_from_file

        return load_dist_from_file(distribution)
    dist_module = importlib.import_module(
        f"pydcop_tpu.distribution.{distribution}"
    )
    return dist_module.distribute(
        cg, dcop.agents.values(), hints=dcop.dist_hints,
        computation_memory=getattr(
            algo_module, "computation_memory", None),
        communication_load=getattr(
            algo_module, "communication_load", None),
    )


def run_local_thread_dcop(algo: AlgorithmDef, cg, distribution, dcop,
                          infinity=float("inf"), delay=None,
                          replication: bool = False,
                          ui_port: Optional[int] = None,
                          collector=None,
                          collect_moment: str = "value_change",
                          collect_period: float = 1.0,
                          repair_mode: str = "device",
                          comm_wrapper=None,
                          health=None,
                          ) -> Orchestrator:
    """One OrchestratedAgent thread per AgentDef + an orchestrator, all
    with in-process transports (reference run.py:145).  With
    ``replication=True`` agents are resilient: they host a
    replica-placement computation for dynamic-DCOP repair.

    ``comm_wrapper(layer, agent_name)`` decorates each AGENT transport
    before the agent is built — the fault-injection seam
    (resilience.faults.FaultPlan.wrapper); the orchestrator's own
    transport is never wrapped, so control-plane bootstrap stays
    reliable.  Started agents are registered in
    ``orchestrator.local_agents`` so crash injection (and tests) can
    reach their threads.

    ``health`` (a resilience.health.HealthConfig) enables active
    failure detection: every started agent gets a HeartbeatEmitter
    (beats ride the agent's — possibly fault-wrapped — transport) and
    the orchestrator a HealthMonitor whose death verdicts feed
    ``report_agent_failure``, i.e. the replication/reparation path.
    The monitor is created here but NOT started; the caller starts it
    once the run begins and stops it before tearing agents down
    (solve_with_agents does both)."""
    comm = InProcessCommunicationLayer()
    orchestrator = Orchestrator(
        algo, cg, distribution, comm, dcop, infinity,
        collector=collector, collect_moment=collect_moment,
        collect_period=collect_period, repair_mode=repair_mode,
    )
    orchestrator.start()
    monitor = None
    if health is not None:
        from pydcop_tpu.resilience.health import attach_health

        monitor = attach_health(orchestrator, health)
    hosting = {
        a for a in distribution.agents
        if distribution.computations_hosted(a)
    }
    def _start_agent(agent_def, ui=None):
        agent_comm = InProcessCommunicationLayer()
        if comm_wrapper is not None:
            agent_comm = comm_wrapper(agent_comm, agent_def.name)
        agent = OrchestratedAgent(
            agent_def, agent_comm, orchestrator.address, delay=delay,
            replication=replication, ui_port=ui,
        )
        if monitor is not None:
            from pydcop_tpu.resilience.health import (
                HEALTH_COMP,
                HeartbeatEmitter,
            )

            # Route heartbeats: the health computation lives on the
            # orchestrator agent but is never published through
            # discovery (service name), so seed the mapping like
            # OrchestratedAgent does for ORCHESTRATOR_MGT.
            agent.discovery.register_computation(
                HEALTH_COMP, ORCHESTRATOR_AGENT, orchestrator.address,
                publish=False,
            )
            emitter = HeartbeatEmitter(
                agent_def.name, monitor.config.interval)
            agent.add_computation(emitter)
            emitter.start()
            monitor.watch(agent_def.name)
        agent.start()
        orchestrator.local_agents[agent_def.name] = agent
        return agent

    for agent_def in dcop.agents.values():
        if agent_def.name not in hosting and not replication:
            continue
        _start_agent(agent_def, ui_port)
        if ui_port:
            ui_port += 1
    # add_agent scenario events create fresh agents through this hook.
    orchestrator.agent_factory = _start_agent
    return orchestrator


def _process_agent_main(agent_def, port: int, orchestrator_address,
                        replication: bool = False,
                        delay=None):
    """Child-process entry: one agent on its own HTTP transport
    (reference run.py:268 _build_process_agent)."""
    import time as _time

    from pydcop_tpu.infrastructure.communication import (
        HttpCommunicationLayer,
    )

    comm = HttpCommunicationLayer(("127.0.0.1", port))
    agent = OrchestratedAgent(
        agent_def, comm, tuple(orchestrator_address),
        replication=replication, delay=delay,
    )
    agent.start()
    # Keep the process alive until the agent thread stops (StopAgent).
    while agent._thread.is_alive():
        agent.join(1.0)
    _time.sleep(0.2)  # let the final AgentStopped POST drain
    comm.shutdown()


def run_local_process_dcop(algo: AlgorithmDef, cg, distribution, dcop,
                           infinity=float("inf"),
                           replication: bool = False,
                           port: int = 9000,
                           collector=None,
                           collect_moment: str = "value_change",
                           collect_period: float = 1.0,
                           repair_mode: str = "device",
                           delay=None) -> Orchestrator:
    """One OS process per agent, JSON-over-HTTP transports on localhost
    ports (reference run.py:225) — the single-host stand-in for true
    multi-machine deployments.  Scenario ``add_agent`` events spawn
    fresh agent processes through ``orchestrator.agent_factory``."""
    import multiprocessing

    from pydcop_tpu.infrastructure.communication import (
        HttpCommunicationLayer,
    )

    comm = HttpCommunicationLayer(("127.0.0.1", port))
    orchestrator = Orchestrator(
        algo, cg, distribution, comm, dcop, infinity,
        collector=collector, collect_moment=collect_moment,
        collect_period=collect_period, repair_mode=repair_mode,
    )
    orchestrator.start()
    ctx = multiprocessing.get_context("spawn")
    next_port = [port]

    def _spawn_agent(agent_def):
        next_port[0] += 1
        p = ctx.Process(
            target=_process_agent_main,
            name=f"p_{agent_def.name}",
            args=(agent_def, next_port[0], orchestrator.address),
            kwargs={"replication": replication, "delay": delay},
            daemon=True,
        )
        p.start()
        return p

    for agent_def in dcop.agents.values():
        if not distribution.computations_hosted(agent_def.name) \
                and not replication:
            continue
        _spawn_agent(agent_def)
    orchestrator.agent_factory = _spawn_agent
    orchestrator.agent_ready_timeout = PROCESS_READY_TIMEOUT
    return orchestrator


def solve(dcop: DCOP, algo_def, distribution="oneagent",
          timeout: Optional[float] = 5, delay=None) -> Dict:
    """One-call solve with the threaded runtime; returns the assignment
    (reference run.py:52)."""
    if isinstance(algo_def, str):
        algo_def = AlgorithmDef.build_with_default_param(
            algo_def, mode=dcop.objective
        )
    algo_module = load_algorithm_module(algo_def.algo)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    if isinstance(distribution, str):
        distribution = _build_distribution(
            dcop, cg, algo_module, distribution)
    orchestrator = run_local_thread_dcop(
        algo_def, cg, distribution, dcop, delay=delay
    )
    try:
        orchestrator.deploy_computations()
        orchestrator.run(timeout=timeout)
        assignment = orchestrator.end_metrics()["assignment"]
        return assignment
    finally:
        orchestrator.stop_agents(5)
        orchestrator.stop()


def solve_with_agents(dcop: DCOP, algo_def, distribution="oneagent",
                      timeout: Optional[float] = 5,
                      max_cycles: int = 0,
                      mode: str = "thread",
                      ui_port: Optional[int] = None,
                      collector=None,
                      collect_moment: str = "value_change",
                      collect_period: float = 1.0,
                      delay: Optional[float] = None,
                      fault_plan=None,
                      health_config=None,
                      metrics_file: Optional[str] = None,
                      metrics_every: Optional[int] = None,
                      metrics_live: bool = False) -> Dict:
    """Full-metrics variant used by the api/CLI thread backend.

    ``fault_plan`` (a resilience.faults.FaultPlan) turns the run into
    a chaos run: agent transports are wrapped with seeded message
    faults, and a crash schedule in the plan enables replication,
    places ``fault_plan.replicas`` replicas before the run and fires
    the kills from a FaultMonitor — the murdered agents' computations
    migrate through the reparation path.  Thread mode only (process
    agents own their transports in other processes).

    ``health_config`` (a resilience.health.HealthConfig) adds active
    failure detection: heartbeat emitters on every agent, a
    HealthMonitor on the orchestrator, and a ``health`` summary
    (statuses + verdict history) in the result.  With BOTH a health
    config and a crash schedule, the kills are SILENT (the fault
    monitor stops the thread but does not report the failure) — the
    heartbeat detector must notice the death and trigger the repair,
    which is the self-healing property the chaos soak asserts.  Thread
    mode only.

    ``metrics_file`` appends a JSONL metrics snapshot (observability
    registry) each time the orchestrator's global cycle view advances
    by ``metrics_every`` cycles, including the cost of the then-current
    assignment."""
    if isinstance(algo_def, str):
        algo_def = AlgorithmDef.build_with_default_param(
            algo_def, mode=dcop.objective
        )
    algo_module = load_algorithm_module(algo_def.algo)
    # Fail in the caller, not on an agent thread during deployment:
    # only the dynamic maxsum computations subscribe to external
    # (read-only) variables; other algorithms would silently treat them
    # as free optimization variables.
    if dcop.external_variables and algo_def.algo != "maxsum_dynamic":
        raise ValueError(
            f"DCOP has external variable(s) "
            f"{sorted(dcop.external_variables)} but algorithm "
            f"{algo_def.algo!r} does not support them: use "
            "'maxsum_dynamic'"
        )
    # Map max_cycles onto the algorithm's stop_cycle parameter when it
    # has one and none was given, so the -c CLI bound takes effect.
    if max_cycles:
        param_names = {p.name for p in algo_module.algo_params}
        if ("stop_cycle" in param_names
                and not algo_def.params.get("stop_cycle")):
            params = algo_def.params
            params["stop_cycle"] = max_cycles
            algo_def = AlgorithmDef(algo_def.algo, params, algo_def.mode)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    if isinstance(distribution, str):
        distribution = _build_distribution(
            dcop, cg, algo_module, distribution)
    if fault_plan is not None and mode != "thread":
        raise ValueError(
            "fault injection needs in-process transports: "
            f"mode must be 'thread', got {mode!r}"
        )
    if health_config is not None and mode != "thread":
        raise ValueError(
            "heartbeat health monitoring instruments in-process "
            f"agents: mode must be 'thread', got {mode!r}"
        )
    comm_wrapper = None
    fault_stats = None
    if fault_plan is not None:
        from pydcop_tpu.resilience.faults import FaultStats

        fault_stats = FaultStats()
        comm_wrapper = fault_plan.wrapper(fault_stats)
    if mode == "process":
        orchestrator = run_local_process_dcop(
            algo_def, cg, distribution, dcop, delay=delay,
            collector=collector, collect_moment=collect_moment,
            collect_period=collect_period,
        )
    else:
        orchestrator = run_local_thread_dcop(
            algo_def, cg, distribution, dcop, ui_port=ui_port,
            delay=delay,
            collector=collector, collect_moment=collect_moment,
            collect_period=collect_period,
            replication=bool(
                fault_plan is not None and fault_plan.crashes),
            comm_wrapper=comm_wrapper,
            health=health_config,
        )
    if metrics_file is not None or metrics_live:
        from pydcop_tpu.observability.metrics import CycleSnapshotter

        # metrics_live (no file): a serve-only run still needs the
        # snapshotter — it is what feeds the live endpoint's cycle/
        # cost metrics and /events stream (path=None writes nothing).
        orchestrator.metrics_snapshotter = CycleSnapshotter(
            metrics_file, every=metrics_every or 1,
            cost_fn=lambda: orchestrator.current_global_cost()[0],
        )
    stopped = False
    monitor = None
    health_monitor = getattr(orchestrator, "health_monitor", None)
    try:
        if not orchestrator.wait_ready(
                PROCESS_READY_TIMEOUT if mode == "process"
                else THREAD_READY_TIMEOUT):
            raise RuntimeError("Agents did not become ready in time")
        orchestrator.deploy_computations()
        if health_monitor is not None:
            health_monitor.start()
            # The live telemetry endpoint's /healthz reads whichever
            # monitor is currently registered (cleared in the finally
            # below, so verdicts never outlive their run).
            from pydcop_tpu.observability.server import (
                set_health_provider,
            )

            set_health_provider(health_monitor.summary)
        if fault_plan is not None and fault_plan.crashes:
            from pydcop_tpu.resilience.faults import (
                CrashSchedule,
                FaultMonitor,
                kill_agent,
            )

            # Replicas must exist before the first kill, or the
            # murdered computations are lost instead of migrated.
            orchestrator.start_replication(fault_plan.replicas)
            kill = kill_agent
            if health_monitor is not None:
                # Silent crash: the thread dies but nobody files the
                # report — detection is the heartbeat monitor's job.
                def kill(orch, agent):
                    kill_agent(orch, agent, report=False)
            monitor = FaultMonitor(
                orchestrator, CrashSchedule(list(fault_plan.crashes)),
                kill=kill,
            ).start()
        orchestrator.run(timeout=timeout)
        # Verdicts must not fire on the clean shutdown below (stopped
        # agents stop beating); detection is over once the run is.
        if health_monitor is not None:
            health_monitor.stop()
        # Stop agents first: final metrics arrive with AgentStopped.
        orchestrator.stop_agents(5)
        stopped = True
        metrics = orchestrator.end_metrics()
        extra = {}
        if fault_stats is not None:
            extra["fault_stats"] = fault_stats.as_dict()
            extra["killed_agents"] = (
                list(monitor.killed) if monitor is not None else []
            )
        if health_monitor is not None:
            extra["health"] = health_monitor.summary()
        return {
            **extra,
            "status": orchestrator.status,
            "assignment": {
                k: v for k, v in metrics["assignment"].items()
                if k in dcop.variables
            },
            "cost": metrics["cost"],
            "violations": metrics["violation"],
            "cycles": metrics["cycle"],
            "time": metrics["time"],
            "msg_count": metrics["msg_count"],
            "msg_size": metrics["msg_size"],
            "agt_metrics": metrics["agt_metrics"],
            "backend": mode,
        }
    finally:
        if monitor is not None:
            monitor.stop()
        if health_monitor is not None:
            health_monitor.stop()
            from pydcop_tpu.observability.server import (
                set_health_provider,
            )

            set_health_provider(None)
        if not stopped:
            orchestrator.stop_agents(5)
        orchestrator.stop()

"""Admission control for the solve service: backpressure + breaker.

Two rejection modes, mapped to distinct HTTP statuses by the front
end (serving/http.py):

- **Queue backpressure (429).** The request queue has a high-water
  mark; a submit that would push the depth past it is rejected
  *immediately* with :class:`QueueFull` — the client learns to back
  off now, instead of its request rotting in an unbounded queue (the
  overload failure mode the ISSUE forbids: a 429, never a hang or a
  silently dropped request).

- **Circuit breaker (503).** Repeated dispatch failures (engine
  errors, ``RecoveryExhausted``) trip a PR-1
  :class:`~pydcop_tpu.resilience.retry.CircuitBreaker`; while it is
  open every submit is rejected with :class:`ServiceUnavailable` so a
  sick engine sheds load instead of queueing doomed work.  After the
  reset timeout the breaker half-opens and the next dispatched batch
  is the probe: its outcome closes or re-opens the circuit.

Every rejection is counted in ``pydcop_requests_total{status}`` by
the service, so the request ledger balances even under overload.
"""

from dataclasses import dataclass
from typing import Optional

from pydcop_tpu.resilience.retry import CircuitBreaker


class AdmissionRejected(Exception):
    """Base: the request was refused at the door.  ``http_status``
    maps the subclass onto the wire."""

    http_status = 503


class QueueFull(AdmissionRejected):
    """Queue depth at/above the high-water mark: back off and retry."""

    http_status = 429


class ServiceUnavailable(AdmissionRejected):
    """The dispatch breaker is open: the engine is failing."""

    http_status = 503


@dataclass(frozen=True)
class AdmissionPolicy:
    """Knobs: ``high_water`` is the queue-depth rejection threshold;
    the breaker fields mirror CircuitBreaker's."""

    high_water: int = 256
    breaker_failures: int = 3
    breaker_reset_s: float = 5.0


class AdmissionController:
    """Stateless depth check + the service's dispatch breaker.

    The breaker is shared with the dispatch path: the scheduler calls
    :meth:`record_dispatch` after every batch, and :meth:`admit`
    refuses while the circuit is open.  Half-open intentionally
    admits — the next dispatch is the recovery probe.
    """

    def __init__(self, policy: Optional[AdmissionPolicy] = None):
        self.policy = policy or AdmissionPolicy()
        self.breaker = CircuitBreaker(
            failure_threshold=self.policy.breaker_failures,
            reset_timeout=self.policy.breaker_reset_s,
            name="serve_dispatch",
        )

    def admit(self, queue_depth: int) -> None:
        """Raise the matching :class:`AdmissionRejected` subclass when
        the request must be refused; return silently otherwise."""
        if self.breaker.state == "open":
            raise ServiceUnavailable(
                "dispatch circuit open after repeated engine failures; "
                f"retry after {self.policy.breaker_reset_s}s"
            )
        if queue_depth >= self.policy.high_water:
            raise QueueFull(
                f"request queue at high-water mark "
                f"({queue_depth}/{self.policy.high_water}); back off"
            )

    def record_dispatch(self, ok: bool) -> None:
        if ok:
            self.breaker.record_success()
            return
        was_open = self.breaker.state == "open"
        self.breaker.record_failure()
        if not was_open and self.breaker.state == "open":
            # The service just went 503: a postmortem bundle now
            # holds the dispatch failures that tripped the circuit.
            from pydcop_tpu.observability import flight

            flight.trigger(
                "breaker_open", breaker="serve_dispatch",
                failure_threshold=self.policy.breaker_failures)

    @property
    def breaker_state(self) -> str:
        return self.breaker.state

"""IoT benchmark generator: scale-free constraint graph, random costs.

Reference parity: pydcop/commands/generators/iot.py (power-law graphs,
binary constraints with random costs, one agent per variable).
"""

from typing import Optional

import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.generators.graphs import scalefree_graph


def generate_iot(
    num_devices: int,
    domain_size: int = 3,
    m_edge: int = 2,
    range_cost: int = 10,
    seed: Optional[int] = None,
) -> DCOP:
    rng = np.random.default_rng(seed)
    domain = Domain("d", "action", list(range(domain_size)))
    variables = [
        Variable(f"v{i:04d}", domain) for i in range(num_devices)
    ]
    dcop = DCOP(f"iot_{num_devices}", objective="min")
    for v in variables:
        dcop.add_variable(v)
    for k, (i, j) in enumerate(
        scalefree_graph(num_devices, m_edge, seed=seed)
    ):
        table = rng.integers(
            0, range_cost, size=(domain_size, domain_size)
        ).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], table, f"c{k}"))
    dcop.add_agents([
        AgentDef(f"a{i:04d}", capacity=100) for i in range(num_devices)
    ])
    return dcop

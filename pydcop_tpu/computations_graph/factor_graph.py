"""Bipartite factor graph: one node per variable, one per constraint.

Reference parity: pydcop/computations_graph/factor_graph.py
(FactorComputationNode :45, VariableComputationNode :104, FactorGraphLink
:161, ComputationsFactorGraph :210, build_computation_graph :245).
Used by: maxsum, amaxsum, maxsum_dynamic.
"""

from typing import Iterable, List, Optional

from pydcop_tpu.computations_graph.objects import (
    ComputationGraph,
    ComputationNode,
    Link,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import ExternalVariable, Variable
from pydcop_tpu.dcop.relations import Constraint

GRAPH_NODE_TYPE_VARIABLE = "VariableComputation"
GRAPH_NODE_TYPE_FACTOR = "FactorComputation"


class FactorGraphLink(Link):
    """A link between one variable node and one factor node."""

    def __init__(self, factor_node: str, variable_node: str):
        super().__init__([factor_node, variable_node], "factor_graph")
        self._factor_node = factor_node
        self._variable_node = variable_node

    @property
    def factor_node(self) -> str:
        return self._factor_node

    @property
    def variable_node(self) -> str:
        return self._variable_node

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "factor_node": self._factor_node,
            "variable_node": self._variable_node,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["factor_node"], r["variable_node"])


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable,
                 links: Optional[Iterable[FactorGraphLink]] = None):
        super().__init__(variable.name, GRAPH_NODE_TYPE_VARIABLE, links)
        self._variable = variable

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def factors(self) -> List[str]:
        """Names of neighbor factor computations."""
        return [l.factor_node for l in self.links]


class FactorComputationNode(ComputationNode):
    def __init__(self, factor: Constraint,
                 links: Optional[Iterable[FactorGraphLink]] = None):
        super().__init__(factor.name, GRAPH_NODE_TYPE_FACTOR, links)
        self._factor = factor

    @property
    def factor(self) -> Constraint:
        return self._factor

    @property
    def variables(self) -> List[Variable]:
        return self._factor.dimensions


class ComputationsFactorGraph(ComputationGraph):
    def __init__(self, var_nodes: Iterable[VariableComputationNode],
                 factor_nodes: Iterable[FactorComputationNode]):
        var_nodes, factor_nodes = list(var_nodes), list(factor_nodes)
        super().__init__("factor_graph", var_nodes + factor_nodes)
        self.variable_nodes = var_nodes
        self.factor_nodes = factor_nodes

    def density(self) -> float:
        """Bipartite density: links / (|vars| * |factors|)."""
        possible = len(self.variable_nodes) * len(self.factor_nodes)
        if not possible:
            return 0.0
        return len(self.links) / possible


def build_computation_graph(
        dcop: Optional[DCOP] = None,
        variables: Optional[Iterable[Variable]] = None,
        constraints: Optional[Iterable[Constraint]] = None,
) -> ComputationsFactorGraph:
    """One variable node per variable, one factor node per constraint,
    one link per (constraint, variable-in-scope) pair."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    links_by_var = {v.name: [] for v in variables}
    factor_nodes = []
    for c in constraints:
        links = []
        for v in c.dimensions:
            if v.name not in links_by_var:
                # External (read-only) variables get no computation node
                # — dynamic factors subscribe to them instead (reference
                # factor_graph.py:276: only listed variables get nodes).
                if isinstance(v, ExternalVariable):
                    continue
                raise ValueError(
                    f"Constraint {c.name} references unknown variable "
                    f"{v.name}"
                )
            link = FactorGraphLink(c.name, v.name)
            links.append(link)
            links_by_var[v.name].append(link)
        factor_nodes.append(FactorComputationNode(c, links))
    var_nodes = [
        VariableComputationNode(v, links_by_var[v.name]) for v in variables
    ]
    return ComputationsFactorGraph(var_nodes, factor_nodes)


def computation_memory(node: ComputationNode) -> float:
    """Footprint estimate: sum of neighbor message sizes (domain sizes)."""
    if isinstance(node, VariableComputationNode):
        return len(node.variable.domain) * len(node.links)
    if isinstance(node, FactorComputationNode):
        return sum(len(v.domain) for v in node.variables)
    raise TypeError(f"Unsupported node {node}")


def communication_load(src: ComputationNode, target: str) -> float:
    """Message size between two adjacent computations: one cost table."""
    if isinstance(src, VariableComputationNode):
        return len(src.variable.domain) + 1
    if isinstance(src, FactorComputationNode):
        for v in src.variables:
            if v.name == target:
                return len(v.domain) + 1
        raise ValueError(f"{target} not a neighbor of factor {src.name}")
    raise TypeError(f"Unsupported node {src}")

"""Discovery: name service mapping agents to addresses and computations
to agents.

Reference parity: pydcop/infrastructure/discovery.py (Directory :294 —
central registry on the orchestrator agent, DirectoryComputation :121;
per-agent Discovery :654 cache with callbacks: register_agent :770,
register_computation :1083, subscribe_computation :1212,
computation_agent :1034, agent_address :746; replica registry
:1304/:1397).

Everything is message-based (works identically over the in-process and
HTTP transports): agents register/subscribe through their
DiscoveryComputation, the directory publishes changes to subscribers.
"""

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Set

from pydcop_tpu.infrastructure.communication import MSG_DISCOVERY
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
    Message,
    message_type,
    register,
)

logger = logging.getLogger("pydcop.discovery")

DIRECTORY_COMP = "_directory"


class UnknownAgent(Exception):
    pass


class UnknownComputation(Exception):
    pass


class DiscoveryException(Exception):
    pass


RegisterAgentMessage = message_type(
    "register_agent", ["agent", "address"])
UnregisterAgentMessage = message_type(
    "unregister_agent", ["agent"])
RegisterComputationMessage = message_type(
    "register_computation", ["computation", "agent", "address"])
UnregisterComputationMessage = message_type(
    "unregister_computation", ["computation", "agent"])
SubscribeMessage = message_type(
    "subscribe", ["kind", "name", "subscribe"])
PublishMessage = message_type(
    "publish", ["event", "name", "value"])
RegisterReplicaMessage = message_type(
    "register_replica", ["replica", "agent", "add"])


class DirectoryComputation(MessagePassingComputation):
    """The central registry, hosted on the directory (orchestrator)
    agent.  When given the hosting agent's Discovery, every change is
    mirrored into it (same-process shortcut: the directory agent sees
    everything without subscribing to itself)."""

    def __init__(self, name: str = DIRECTORY_COMP,
                 local_discovery: Optional["Discovery"] = None):
        super().__init__(name)
        self.local_discovery = local_discovery
        self.agents: Dict[str, Any] = {}
        self.computations: Dict[str, str] = {}
        self.replicas: Dict[str, Set[str]] = {}
        # subscriptions: kind -> name -> set of subscriber computations
        self._subs: Dict[str, Dict[str, Set[str]]] = {
            "agent": {}, "computation": {}, "replica": {},
        }

    def _publish(self, kind: str, event: str, name: str, value):
        if self.local_discovery is not None:
            self.local_discovery._on_publish(event, name, value)
        for sub in self._subs[kind].get(name, set()) | \
                self._subs[kind].get("*", set()):
            self.post_msg(
                sub, PublishMessage(event, name, value), MSG_DISCOVERY
            )

    @register("register_agent")
    def _on_register_agent(self, sender, msg, t):
        self.agents[msg.agent] = msg.address
        self._publish("agent", "agent_added", msg.agent, msg.address)

    @register("unregister_agent")
    def _on_unregister_agent(self, sender, msg, t):
        self.agents.pop(msg.agent, None)
        self._publish("agent", "agent_removed", msg.agent, None)

    @register("register_computation")
    def _on_register_computation(self, sender, msg, t):
        self.computations[msg.computation] = msg.agent
        if msg.address is not None:
            self.agents[msg.agent] = msg.address
        self._publish(
            "computation", "computation_added", msg.computation,
            (msg.agent, self.agents.get(msg.agent)),
        )

    @register("unregister_computation")
    def _on_unregister_computation(self, sender, msg, t):
        self.computations.pop(msg.computation, None)
        self._publish(
            "computation", "computation_removed", msg.computation, None
        )

    @register("register_replica")
    def _on_register_replica(self, sender, msg, t):
        group = self.replicas.setdefault(msg.replica, set())
        if msg.add:
            group.add(msg.agent)
        else:
            group.discard(msg.agent)
        self._publish(
            "replica", "replica_changed", msg.replica, sorted(group)
        )

    @register("subscribe")
    def _on_subscribe(self, sender, msg, t):
        subs = self._subs[msg.kind].setdefault(msg.name, set())
        if msg.subscribe:
            subs.add(sender)
            # Answer with current state so the subscriber syncs up.
            if msg.kind == "agent":
                if msg.name in self.agents:
                    self.post_msg(sender, PublishMessage(
                        "agent_added", msg.name, self.agents[msg.name]
                    ), MSG_DISCOVERY)
            elif msg.kind == "computation":
                if msg.name in self.computations:
                    agt = self.computations[msg.name]
                    self.post_msg(sender, PublishMessage(
                        "computation_added", msg.name,
                        (agt, self.agents.get(agt)),
                    ), MSG_DISCOVERY)
            elif msg.kind == "replica":
                if msg.name in self.replicas:
                    self.post_msg(sender, PublishMessage(
                        "replica_changed", msg.name,
                        sorted(self.replicas[msg.name]),
                    ), MSG_DISCOVERY)
        else:
            subs.discard(sender)


class Directory:
    """Convenience wrapper owning the DirectoryComputation (reference
    discovery.py:294)."""

    def __init__(self, discovery: "Discovery"):
        self.discovery = discovery
        self.directory_computation = DirectoryComputation(
            local_discovery=discovery
        )

    @property
    def address(self):
        return self.discovery.agent_address(self.discovery.agent_name)


class DiscoveryComputation(MessagePassingComputation):
    """Per-agent client computation receiving directory publications."""

    def __init__(self, discovery: "Discovery", agent_name: str):
        super().__init__(f"_discovery_{agent_name}")
        self._discovery = discovery

    @register("publish")
    def _on_publish(self, sender, msg, t):
        self._discovery._on_publish(msg.event, msg.name, msg.value)


class Discovery:
    """Per-agent discovery cache + client API.

    The cache is pre-seeded with the directory agent's address at agent
    construction (bootstrap) and kept in sync through publications.
    """

    def __init__(self, agent_name: str, address):
        self.agent_name = agent_name
        self.discovery_computation = DiscoveryComputation(self, agent_name)
        self._agents: Dict[str, Any] = {agent_name: address}
        self._computations: Dict[str, str] = {}
        self._replicas: Dict[str, List[str]] = {}
        self._lock = threading.RLock()
        # callbacks: name -> list of cb(event, name, value)
        self._agent_cbs: Dict[str, List[Callable]] = {}
        self._computation_cbs: Dict[str, List[Callable]] = {}
        self._replica_cbs: Dict[str, List[Callable]] = {}
        self.directory_agent: Optional[str] = None
        # Global hooks cb(event, agent_name) fired on every agent
        # add/remove (local or published) — used by transports to purge
        # retry queues for departed agents.
        self.agent_change_hooks: List[Callable] = []

    # -- wiring -------------------------------------------------------- #

    def use_directory(self, agent_name: str, address):
        """Point this discovery at the directory agent (reference
        :707).  Seeds the cache so directory-bound messages resolve."""
        self.directory_agent = agent_name
        with self._lock:
            self._agents[agent_name] = address
            self._computations[DIRECTORY_COMP] = agent_name

    def _send_to_directory(self, msg: Message):
        if self.directory_agent is None:
            return  # standalone mode: local cache only
        self.discovery_computation.post_msg(
            DIRECTORY_COMP, msg, MSG_DISCOVERY
        )

    # -- registration -------------------------------------------------- #

    def register_agent(self, agent_name: str, address,
                       publish: bool = True):
        with self._lock:
            self._agents[agent_name] = address
        self._fire_agent_change("agent_added", agent_name)
        if publish:
            self._send_to_directory(
                RegisterAgentMessage(agent_name, address))

    def unregister_agent(self, agent_name: str, publish: bool = True):
        with self._lock:
            self._agents.pop(agent_name, None)
        self._fire_agent_change("agent_removed", agent_name)
        if publish:
            self._send_to_directory(UnregisterAgentMessage(agent_name))

    def _fire_agent_change(self, event: str, agent_name: str):
        for hook in self.agent_change_hooks:
            try:
                hook(event, agent_name)
            except Exception:
                logger.exception(
                    "Agent-change hook error for %s %s", event, agent_name
                )

    def register_computation(self, computation: str,
                             agent_name: Optional[str] = None,
                             address=None, publish: bool = True):
        agent_name = agent_name or self.agent_name
        with self._lock:
            self._computations[computation] = agent_name
            if address is not None:
                self._agents[agent_name] = address
        if publish:
            self._send_to_directory(RegisterComputationMessage(
                computation, agent_name,
                address if address is not None
                else self._agents.get(agent_name),
            ))

    def unregister_computation(self, computation: str,
                               agent_name: Optional[str] = None,
                               publish: bool = True):
        with self._lock:
            self._computations.pop(computation, None)
        if publish:
            self._send_to_directory(UnregisterComputationMessage(
                computation, agent_name or self.agent_name))

    def register_replica(self, replica: str, agent_name: str):
        self._send_to_directory(
            RegisterReplicaMessage(replica, agent_name, True))

    def unregister_replica(self, replica: str, agent_name: str):
        self._send_to_directory(
            RegisterReplicaMessage(replica, agent_name, False))

    # -- lookups ------------------------------------------------------- #

    def agents(self) -> List[str]:
        with self._lock:
            return list(self._agents)

    def computations(self) -> List[str]:
        with self._lock:
            return list(self._computations)

    def agent_address(self, agent_name: str):
        with self._lock:
            try:
                return self._agents[agent_name]
            except KeyError:
                raise UnknownAgent(agent_name)

    def computation_agent(self, computation: str) -> str:
        with self._lock:
            try:
                return self._computations[computation]
            except KeyError:
                raise KeyError(computation)

    def replica_agents(self, replica: str) -> List[str]:
        with self._lock:
            return list(self._replicas.get(replica, []))

    # -- subscriptions ------------------------------------------------- #

    def subscribe_agent(self, agent_name: str,
                        cb: Optional[Callable] = None):
        if cb:
            self._agent_cbs.setdefault(agent_name, []).append(cb)
        self._send_to_directory(SubscribeMessage("agent", agent_name, True))

    def subscribe_computation(self, computation: str,
                              cb: Optional[Callable] = None):
        if cb:
            self._computation_cbs.setdefault(computation, []).append(cb)
        self._send_to_directory(
            SubscribeMessage("computation", computation, True))

    def subscribe_replica(self, replica: str,
                          cb: Optional[Callable] = None):
        if cb:
            self._replica_cbs.setdefault(replica, []).append(cb)
        self._send_to_directory(SubscribeMessage("replica", replica, True))

    def unsubscribe_computation(self, computation: str):
        self._computation_cbs.pop(computation, None)
        self._send_to_directory(
            SubscribeMessage("computation", computation, False))

    # -- publication handling ------------------------------------------ #

    def _on_publish(self, event: str, name: str, value):
        def with_wildcard(cb_map: Dict[str, List[Callable]]):
            # "*" subscriptions receive every publication of the kind
            # (the directory side already fans them out; mirror that
            # here for locally-registered callbacks).
            return list(cb_map.get(name, [])) + list(
                cb_map.get("*", []))

        cbs: List[Callable] = []
        with self._lock:
            if event == "agent_added":
                self._agents[name] = value
                cbs = with_wildcard(self._agent_cbs)
            elif event == "agent_removed":
                self._agents.pop(name, None)
                cbs = with_wildcard(self._agent_cbs)
            elif event == "computation_added":
                agent, address = value
                self._computations[name] = agent
                if address is not None:
                    self._agents[agent] = address
                value = agent
                cbs = with_wildcard(self._computation_cbs)
            elif event == "computation_removed":
                self._computations.pop(name, None)
                cbs = with_wildcard(self._computation_cbs)
            elif event == "replica_changed":
                self._replicas[name] = list(value)
                cbs = with_wildcard(self._replica_cbs)
        if event in ("agent_added", "agent_removed"):
            self._fire_agent_change(event, name)
        for cb in cbs:
            try:
                cb(event, name, value)
            except Exception:
                logger.exception(
                    "Discovery callback error for %s %s", event, name
                )

"""MixedDSA step kernel — DSA for problems mixing hard and soft
constraints.

Reference parity: pydcop/algorithms/mixeddsa.py:154-470.  A constraint
is *hard* when its table contains an infinite cost (mixeddsa.py:215-222
detects ``float('inf')`` while scanning assignments); in the compiled
graph any entry >= BIG (the framework's infinity stand-in) counts.

Per cycle each variable evaluates candidates lexicographically:
first minimize the number of violated hard constraints, then the DCOP
cost *excluding* violated hard constraints' infinities
(_compute_dcop_cost :410, _compute_best_value :381).  Moves
(mixeddsa.py:301-345):

- hard improvement possible (delta_dcsp > 0): move w.p. `proba_hard`;
- only soft improvement (delta_dcsp == 0, delta_dcop > 0): move w.p.
  `proba_soft`;
- no improvement but hard conflicts remain and other optimal values
  exist: move to a different optimum w.p. `proba_hard` (escape, :317);
- no improvement, no hard conflict, but a violated soft constraint
  (cost above its own optimum) and variant B/C: move to a different
  optimum w.p. `proba_soft` (:330).

(The reference's final variant-C branch duplicates an earlier elif
condition and is unreachable; it is intentionally not reproduced.)
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import BIG, CompiledFactorGraph
from pydcop_tpu.ops.localsearch import (
    _fix_other_axes,
    assignment_cost,
    factor_current_costs,
    factor_min_over_valid,
    factor_valid_masks,
    random_best_choice,
    random_initial_values,
)


class MixedDsaState(NamedTuple):
    values: jnp.ndarray  # [V+1] int32
    key: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph, seed: int = 0) -> MixedDsaState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return MixedDsaState(
        values=random_initial_values(k0, graph),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def classify_factors(graph: CompiledFactorGraph
                     ) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]:
    """Per bucket: (hard [F] bool, soft_optimum [F]).

    hard = some valid entry is infinite (>= BIG); soft_optimum = the
    factor's min over the valid region (the reference's boundary,
    mixeddsa.py:209-224), used to detect violated soft constraints.
    Padding rows (all-BIG valid region is empty via the sentinel var's
    all-False validity) come out hard=False, optimum=+inf and are
    harmless: their cost rows are zero.
    """
    out = []
    for bucket, valid in zip(graph.buckets, factor_valid_masks(graph)):
        axes = tuple(range(1, bucket.costs.ndim))
        hard = jnp.any(valid & (bucket.costs >= BIG), axis=axes)
        opt = factor_min_over_valid(bucket, valid)
        out.append((hard, opt))
    return tuple(out)


def _candidate_metrics(graph: CompiledFactorGraph, values: jnp.ndarray,
                       classes) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(nb_viol [V+1, D], cost [V+1, D]): per candidate value, the count
    of violated hard constraints and the DCOP cost without their
    infinities (_compute_dcop_cost, mixeddsa.py:410-446)."""
    n_segments = graph.var_costs.shape[0]
    nb_viol = jnp.zeros_like(graph.var_costs)
    cost = graph.var_costs
    for bucket, (hard, _) in zip(graph.buckets, classes):
        arity = bucket.var_ids.shape[1]
        for p in range(arity):
            fixed = _fix_other_axes(bucket.costs, bucket.var_ids, values, p)
            viol = hard[:, None] & (fixed >= BIG)
            nb_viol = nb_viol + jax.ops.segment_sum(
                viol.astype(jnp.float32), bucket.var_ids[:, p],
                num_segments=n_segments,
            )
            cost = cost + jax.ops.segment_sum(
                jnp.where(viol, 0.0, fixed), bucket.var_ids[:, p],
                num_segments=n_segments,
            )
    return nb_viol, cost


def _soft_violated_vars(graph: CompiledFactorGraph, values: jnp.ndarray,
                        classes) -> jnp.ndarray:
    """[V+1] bool: has an incident soft constraint above its optimum
    (exists_violated_soft_constraint, mixeddsa.py:464)."""
    n_segments = graph.var_costs.shape[0]
    out = jnp.zeros((n_segments,), dtype=jnp.int32)
    for bucket, cur, (hard, opt) in zip(
        graph.buckets, factor_current_costs(graph, values), classes
    ):
        sv = ((~hard) & (cur != opt)).astype(jnp.int32)
        for p in range(bucket.var_ids.shape[1]):
            out = jnp.maximum(out, jax.ops.segment_max(
                sv, bucket.var_ids[:, p], num_segments=n_segments
            ))
    return out > 0


def mixeddsa_step(state: MixedDsaState, graph: CompiledFactorGraph, *,
                  variant: str, proba_hard: float, proba_soft: float,
                  classes) -> MixedDsaState:
    """One lockstep MixedDSA cycle."""
    key, k_choice, k_change = jax.random.split(state.key, 3)
    values = state.values
    valid = graph.var_valid

    nb_viol, cost = _candidate_metrics(graph, values, classes)
    cur_nb = jnp.take_along_axis(nb_viol, values[:, None], axis=1).squeeze(1)
    cur_cost = jnp.take_along_axis(cost, values[:, None], axis=1).squeeze(1)

    # Lexicographic best: fewest violated hard constraints, then cost
    # (_compute_best_value, mixeddsa.py:381-402).
    min_nb = jnp.min(jnp.where(valid, nb_viol, jnp.inf), axis=1)
    tie = valid & (nb_viol == min_nb[:, None])
    best_cost = jnp.min(jnp.where(tie, cost, jnp.inf), axis=1)
    is_best = tie & (cost == best_cost[:, None])
    n_best = jnp.sum(is_best, axis=1)

    delta_dcsp = cur_nb - min_nb
    delta_dcop = cur_cost - best_cost

    one_hot_cur = (
        jnp.arange(cost.shape[1])[None, :] == values[:, None]
    )
    alt_best = is_best & ~one_hot_cur  # bests minus current value

    soft_viol = _soft_violated_vars(graph, values, classes)
    variant_bc = variant in ("B", "C")

    b_hard = delta_dcsp > 0
    b_soft = (delta_dcsp == 0) & (delta_dcop > 0)
    no_improve = (delta_dcsp == 0) & (delta_dcop == 0)
    b_escape_hard = no_improve & (min_nb > 0) & (n_best > 1)
    b_escape_soft = (
        no_improve & (min_nb == 0) & soft_viol & (n_best > 1)
        if variant_bc else jnp.zeros_like(b_hard)
    )

    proba = (
        jnp.where(b_hard | b_escape_hard, proba_hard, 0.0)
        + jnp.where(b_soft | b_escape_soft, proba_soft, 0.0)
    )
    escape = b_escape_hard | b_escape_soft
    choice_mask = jnp.where(escape[:, None], alt_best, is_best)

    new_vals = random_best_choice(k_choice, choice_mask)
    u = jax.random.uniform(k_change, (values.shape[0],))
    values = jnp.where(u < proba, new_vals, values)
    return MixedDsaState(values=values, key=key, cycle=state.cycle + 1)


def run_mixeddsa(graph: CompiledFactorGraph, max_cycles: int, *,
                 variant: str = "B", proba_hard: float = 0.7,
                 proba_soft: float = 0.5, seed: int = 0,
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full MixedDSA run in one XLA program.

    Returns (values [V], final cost incl. hard infinities, cycles)."""
    state = init_state(graph, seed)
    classes = classify_factors(graph)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: mixeddsa_step(
            s, graph, variant=variant, proba_hard=proba_hard,
            proba_soft=proba_soft, classes=classes,
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle

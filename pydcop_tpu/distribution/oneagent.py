"""oneagent distribution: one computation per agent.

Reference parity: pydcop/distribution/oneagent.py (distribute :90,
cost 0 :65) — the classic DCOP hypothesis where each agent controls
exactly one variable/computation.
"""

from typing import Iterable, Optional

from pydcop_tpu.distribution.objects import (
    Distribution,
    ImpossibleDistributionException,
)


def distribute(computation_graph, agentsdef: Iterable,
               hints=None, computation_memory=None,
               communication_load=None, **_) -> Distribution:
    agents = list(agentsdef)
    nodes = computation_graph.nodes
    if len(agents) < len(nodes):
        raise ImpossibleDistributionException(
            f"Need at least {len(nodes)} agents for {len(nodes)} "
            f"computations, got {len(agents)}"
        )
    mapping = {a.name: [] for a in agents}
    for node, agent in zip(nodes, agents):
        mapping[agent.name].append(node.name)
    return Distribution(mapping)


def distribution_cost(distribution: Distribution, computation_graph,
                      agentsdef, computation_memory=None,
                      communication_load=None):
    """(total, comm, hosting) — all zero by definition for oneagent."""
    return 0, 0, 0

"""Unit tests for domains, variables and agent definitions.

Mirrors the reference's test strategy (tests/unit/test_dcop_objects.py):
pure in-memory, no runtime.
"""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import (
    AgentDef,
    BinaryVariable,
    Domain,
    ExternalVariable,
    Variable,
    VariableNoisyCostFunc,
    VariableWithCostDict,
    VariableWithCostFunc,
    create_agents,
    create_binary_variables,
    create_variables,
)
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


class TestDomain:
    def test_basics(self):
        d = Domain("colors", "color", ["R", "G", "B"])
        assert len(d) == 3
        assert d.index("G") == 1
        assert list(d) == ["R", "G", "B"]
        assert d[2] == "B"
        assert "R" in d

    def test_to_domain_value_from_str(self):
        d = Domain("d", "", [1, 2, 3])
        assert d.to_domain_value("2") == (1, 2)

    def test_to_domain_value_unknown_raises(self):
        d = Domain("d", "", [1, 2, 3])
        with pytest.raises(ValueError):
            d.to_domain_value("9")

    def test_equality_and_hash(self):
        d1 = Domain("d", "t", [0, 1])
        d2 = Domain("d", "t", [0, 1])
        assert d1 == d2
        assert hash(d1) == hash(d2)

    def test_simple_repr_roundtrip(self):
        d = Domain("d", "t", [0, 1, 2])
        assert from_repr(simple_repr(d)) == d


class TestVariable:
    def test_basics(self):
        d = Domain("d", "", [0, 1, 2])
        v = Variable("v1", d, initial_value=1)
        assert v.initial_value == 1
        assert v.cost_for_val(0) == 0

    def test_bad_initial_value(self):
        d = Domain("d", "", [0, 1])
        with pytest.raises(ValueError):
            Variable("v1", d, initial_value=5)

    def test_list_domain_wrapped(self):
        v = Variable("v1", [0, 1, 2])
        assert len(v.domain) == 3

    def test_cost_func(self):
        d = Domain("d", "", [0, 1, 2])
        v = VariableWithCostFunc("v1", d, "v1 * 0.5")
        assert v.cost_for_val(2) == 1.0
        assert list(v.cost_vector()) == [0, 0.5, 1.0]

    def test_cost_func_wrong_variable_raises(self):
        d = Domain("d", "", [0, 1])
        with pytest.raises(ValueError):
            VariableWithCostFunc("v1", d, "other * 0.5")

    def test_cost_dict(self):
        d = Domain("d", "", ["a", "b"])
        v = VariableWithCostDict("v1", d, {"a": 1.0, "b": 2.0})
        assert v.cost_for_val("b") == 2.0

    def test_noisy_cost_is_deterministic(self):
        d = Domain("d", "", [0, 1, 2])
        v1 = VariableNoisyCostFunc("v1", d, "v1 * 0.5", noise_level=0.1)
        v2 = VariableNoisyCostFunc("v1", d, "v1 * 0.5", noise_level=0.1)
        assert v1.cost_for_val(1) == v2.cost_for_val(1)
        assert 0.5 <= v1.cost_for_val(1) < 0.6

    def test_noisy_cost_differs_across_vars(self):
        d = Domain("d", "", [0, 1, 2])
        v1 = VariableNoisyCostFunc("v1", d, "v1 * 0", noise_level=0.1)
        v2 = VariableNoisyCostFunc("v2", d, "v2 * 0", noise_level=0.1)
        assert v1.cost_for_val(1) != v2.cost_for_val(1)

    def test_binary_variable(self):
        v = BinaryVariable("b1")
        assert list(v.domain) == [0, 1]

    def test_external_variable_fires_callbacks(self):
        d = Domain("d", "", [True, False])
        ev = ExternalVariable("e1", d, value=True)
        seen = []
        ev.subscribe(seen.append)
        ev.value = False
        assert seen == [False]
        ev.value = False  # no change, no fire
        assert seen == [False]

    def test_simple_repr_roundtrip_cost_func(self):
        d = Domain("d", "", [0, 1, 2])
        v = VariableWithCostFunc("v1", d, "v1 * 0.5", initial_value=1)
        v2 = from_repr(simple_repr(v))
        assert v2.name == "v1"
        assert v2.cost_for_val(2) == 1.0


class TestCreateVariables:
    def test_from_str_list(self):
        d = Domain("d", "", [0, 1])
        vs = create_variables("x_", ["a", "b"], d)
        assert set(vs) == {"x_a", "x_b"}
        assert vs["x_a"].name == "x_a"

    def test_from_ranges(self):
        d = Domain("d", "", [0, 1])
        vs = create_variables("v", [range(2), range(3)], d)
        assert len(vs) == 6
        assert vs[(1, 2)].name == "v1_2"

    def test_binary(self):
        vs = create_binary_variables("b_", [["c1", "c2"], ["a1"]])
        assert vs[("c1", "a1")].name == "b_c1_a1"


class TestAgentDef:
    def test_defaults(self):
        a = AgentDef("a1")
        assert a.capacity == 100
        assert a.route("a2") == 1
        assert a.route("a1") == 0
        assert a.hosting_cost("c1") == 0

    def test_extras(self):
        a = AgentDef("a1", capacity=42, foo="bar")
        assert a.capacity == 42
        assert a.foo == "bar"
        with pytest.raises(AttributeError):
            a.baz

    def test_costs_routes(self):
        a = AgentDef(
            "a1",
            default_hosting_cost=5,
            hosting_costs={"c1": 10},
            default_route=2,
            routes={"a2": 7},
        )
        assert a.hosting_cost("c1") == 10
        assert a.hosting_cost("cX") == 5
        assert a.route("a2") == 7
        assert a.route("a3") == 2

    def test_simple_repr_roundtrip(self):
        a = AgentDef("a1", capacity=42, hosting_costs={"c1": 10})
        a2 = from_repr(simple_repr(a))
        assert a2 == a

    def test_create_agents(self):
        agts = create_agents("a", range(3), capacity=50)
        assert set(agts) == {"a0", "a1", "a2"}
        assert agts["a1"].capacity == 50

"""Battery for the request-scoped observability plane (ISSUE 9):

- **trace context**: ``tracer.context`` binds args (a request's
  ``trace_id``, a dispatch's ``trace_ids``) onto the current thread so
  every span/instant recorded underneath carries them; ``complete``
  records a span from explicit endpoints (the queue wait that starts
  on the submitting thread and ends on the scheduler);
- **request query**: ``query_request`` filters a trace to one
  request's events and rebuilds a well-nested span tree — asserted
  end-to-end through a real ``SolveService`` submit→dispatch→engine
  path and through the ``pydcop trace query`` CLI;
- **latency exemplars**: histogram buckets remember the last trace_id
  per native bucket, exposed in OpenMetrics exemplar syntax and
  resolvable by quantile (the p99 spike → trace hop);
- **flight recorder**: the always-on ring records while file tracing
  is off; anomaly triggers (guard trip, poison bin) dump postmortem
  bundles whose event tail contains the triggering instant (the
  ISSUE 9 anomaly acceptance, battery form); ``pydcop debug bundle``
  cuts one on demand, locally and over HTTP;
- **serve-plane SSE**: a client on ``/events`` sees a submitted
  request's full lifecycle (accepted → dispatched → finished) in
  order, each event carrying the trace_id;
- **/healthz journal backlog**: a journaled service reports
  ``pending_replayable`` + ``journal_bytes`` (replay debt before a
  restart);
- **TraceFileError regressions**: a trace file with a truncated
  header line or a corrupt clock anchor raises a clean error naming
  the file, never a KeyError mid-merge;
- **convergence health**: per-segment message residual and
  assignment-flip-rate, computed at segment boundaries only, landing
  in the gauges, the SSE payload and the result metrics.
"""

import glob
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.observability.flight import (
    FlightRecorder,
    ring_size_from_env,
    set_journal_provider,
)
from pydcop_tpu.observability.metrics import MetricsRegistry
from pydcop_tpu.observability.trace import (
    HEADER_KEY,
    TraceFileError,
    Tracer,
    event_matches_request,
    load_events_aligned,
    load_trace_file,
    merge_traces,
    query_request,
    tracer,
)
from pydcop_tpu.serving.service import SolveService

MAX_CYCLES = 40
PARAMS = {"max_cycles": MAX_CYCLES}


def _instance(n: int, seed: int) -> DCOP:
    """Ring coloring with seeded random tables (the serving battery
    fixture): carries an agent so it survives yaml round-trips."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"rt{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k, (i, j) in enumerate(
            [(i, (i + 1) % n) for i in range(n)]):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _service(**kw) -> SolveService:
    kw.setdefault("batch_window_s", 0.05)
    kw.setdefault("max_batch", 8)
    return SolveService(**kw)


@pytest.fixture
def flight_ring(tmp_path):
    """A fresh recorder attached to the PROCESS tracer (where the
    engine/serving call sites record), restored afterwards."""
    prev = tracer.flight
    recorder = FlightRecorder(events=512,
                              bundle_dir=str(tmp_path / "bundles"))
    tracer.set_flight(recorder)
    yield recorder
    tracer.set_flight(prev)


# ------------------------------------------------------------------ #
# trace context + retroactive spans


class TestTraceContext:
    def test_context_tags_everything_underneath(self):
        t = Tracer()
        t.enable()
        with t.context(trace_id="abc123"):
            with t.span("outer", "x"):
                t.instant("mark", "x")
        with t.span("after", "x"):
            pass
        by_name = {e["name"]: e for e in t.events()}
        assert by_name["outer"]["args"]["trace_id"] == "abc123"
        assert by_name["mark"]["args"]["trace_id"] == "abc123"
        assert "trace_id" not in by_name["after"]["args"], \
            "context leaked past its with-block"

    def test_nested_context_inner_shadows_outer(self):
        t = Tracer()
        t.enable()
        with t.context(trace_id="outer", color="blue"):
            with t.context(trace_id="inner"):
                t.instant("deep", "x")
            t.instant("shallow", "x")
        by_name = {e["name"]: e for e in t.events()}
        assert by_name["deep"]["args"]["trace_id"] == "inner"
        assert by_name["deep"]["args"]["color"] == "blue"
        assert by_name["shallow"]["args"]["trace_id"] == "outer"

    def test_explicit_args_win_over_context(self):
        t = Tracer()
        t.enable()
        with t.context(kind="ctx"):
            t.instant("ev", "x", kind="explicit")
        (ev,) = t.events()
        assert ev["args"]["kind"] == "explicit"

    def test_complete_records_retroactive_span(self):
        t = Tracer()
        t.enable()
        t0 = time.perf_counter()
        t1 = t0 + 0.25
        t.complete("queue_wait", "serving", t0=t0, t1=t1,
                   trace_id="q1")
        (ev,) = t.events()
        assert ev["ph"] == "X"
        assert ev["dur"] == pytest.approx(0.25e6, rel=1e-6)
        assert ev["args"]["trace_id"] == "q1"


# ------------------------------------------------------------------ #
# request query


class TestQueryRequest:
    def _span(self, name, ts, dur, tid=1, **args):
        return {"name": name, "cat": "x", "ph": "X", "ts": ts,
                "dur": dur, "tid": tid, "args": args}

    def _instant(self, name, ts, tid=1, **args):
        return {"name": name, "cat": "x", "ph": "i", "ts": ts,
                "tid": tid, "args": args}

    def test_matches_direct_and_batch_tags(self):
        assert event_matches_request(
            self._span("a", 0, 1, trace_id="t1"), "t1")
        assert event_matches_request(
            self._span("a", 0, 1, trace_ids=["t0", "t1"]), "t1")
        assert not event_matches_request(
            self._span("a", 0, 1, trace_id="t2"), "t1")
        assert not event_matches_request(self._span("a", 0, 1), "t1")

    def test_tree_nests_by_containment_and_filters(self):
        events = [
            self._span("dispatch", 0, 100, trace_ids=["t1"]),
            self._span("engine", 10, 50, trace_ids=["t1"]),
            self._instant("chunk", 20, trace_ids=["t1"]),
            self._span("other_request", 200, 10, trace_id="t2"),
        ]
        tree = query_request(events, "t1")
        assert tree["events"] == 3 and tree["spans"] == 2
        assert tree["well_nested"]
        assert tree["names"] == sorted(["dispatch", "engine",
                                        "chunk"])
        (root,) = tree["tree"]
        assert root["name"] == "dispatch"
        (child,) = root["children"]
        assert child["name"] == "engine"
        assert child["children"][0]["name"] == "chunk"

    def test_cross_lane_request_stitches_in_time_order(self):
        events = [
            self._span("submit", 0, 10, tid=1, trace_id="t1"),
            self._span("dispatch", 20, 30, tid=2,
                       trace_ids=["t1"]),
        ]
        tree = query_request(events, "t1")
        assert tree["lanes"] == 2
        assert [n["name"] for n in tree["tree"]] == ["submit",
                                                     "dispatch"]

    def test_unknown_trace_id_is_empty_not_error(self):
        tree = query_request([self._span("a", 0, 1, trace_id="x")],
                             "nope")
        assert tree["events"] == 0 and tree["tree"] == []


class TestServeRequestTracing:
    """The tentpole end-to-end, in-process: one submit through the
    real service leaves a queryable causal chain."""

    def test_submit_to_engine_chain_is_one_tagged_tree(self):
        tracer.enable()
        svc = _service()
        svc.start()
        try:
            rid = svc.submit(_instance(8, 3), params=PARAMS)
            result = svc.result(rid, wait=60.0)
            assert result is not None
            tid = result["trace_id"]
            assert tid and tid == svc.trace_id(rid)
            events = tracer.events()
        finally:
            svc.stop(drain=False)
            tracer.disable()
        tree = query_request(events, tid)
        assert tree["well_nested"], "request tree not well nested"
        names = set(tree["names"])
        assert {"serve_submit", "serve_queued", "serve_dispatch",
                "engine_segment"} <= names, names

        def _flat(nodes):
            for node in nodes:
                yield node
                yield from _flat(node["children"])

        for node in _flat(tree["tree"]):
            args = node["args"]
            assert (args.get("trace_id") == tid
                    or tid in (args.get("trace_ids") or [])), \
                f"{node['name']} span missing the request tag"

    def test_trace_query_cli_reconstructs_request(self, tmp_path,
                                                  capsys):
        from pydcop_tpu.dcop_cli import main as cli_main

        tracer.enable()
        svc = _service()
        svc.start()
        try:
            rid = svc.submit(_instance(8, 4), params=PARAMS)
            result = svc.result(rid, wait=60.0)
            tid = result["trace_id"]
        finally:
            svc.stop(drain=False)
            path = str(tmp_path / "serve.jsonl")
            tracer.export_jsonl(path)
            tracer.disable()
        rc = cli_main(["trace", "query", "--request", tid,
                       "--json", path])
        assert rc == 0
        tree = json.loads(capsys.readouterr().out)
        assert tree["trace_id"] == tid and tree["well_nested"]
        assert "engine_segment" in tree["names"]
        # Unknown id: empty result, exit 1, not a crash.
        rc = cli_main(["trace", "query", "--request", "feedbeef",
                       "--json", path])
        assert rc == 1


# ------------------------------------------------------------------ #
# latency exemplars


class TestExemplars:
    def test_native_bucket_remembers_last_trace_id(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "x", buckets=(0.1, 1.0, 10.0))
        h.observe(0.05, exemplar="early")
        h.observe(0.07, exemplar="late")  # same bucket: last wins
        h.observe(5.0, exemplar="slow")
        h.observe(0.5)                    # no exemplar: cell kept
        snap = h.snapshot()[0]["exemplars"]
        assert snap["0.1"]["trace_id"] == "late"
        assert snap["10"]["trace_id"] == "slow"
        assert "1" not in snap

    def test_openmetrics_counter_family_drops_total_suffix(self):
        """OpenMetrics forbids ``_total`` in a counter FAMILY name
        (it is the reserved sample suffix): family ``x`` exposes
        sample ``x_total``.  The classic dialect keeps the full name
        in both places."""
        reg = MetricsRegistry()
        reg.counter("req_total", "x").inc()
        om = reg.to_prometheus(openmetrics=True)
        assert "# TYPE req counter" in om
        assert "# HELP req x" in om
        assert "\nreq_total 1" in om
        classic = reg.to_prometheus()
        assert "# TYPE req_total counter" in classic

    def test_classic_text_format_stays_exemplar_free(self):
        """The v0.0.4 parser errors on exemplar suffixes (failing the
        whole scrape), so the classic dialect must never carry
        them."""
        reg = MetricsRegistry()
        h = reg.histogram("lat", "x", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="abc123")
        classic = reg.to_prometheus()
        assert " # {" not in classic
        assert "# EOF" not in classic

    def test_openmetrics_exposition_suffix(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "x", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar="abc123")
        text = reg.to_prometheus(openmetrics=True)
        assert text.rstrip().endswith("# EOF")
        bucket_lines = [ln for ln in text.splitlines()
                        if ln.startswith("lat_bucket")]
        tagged = [ln for ln in bucket_lines
                  if '# {trace_id="abc123"}' in ln]
        assert len(tagged) == 1, (
            "exactly the native bucket carries the exemplar: "
            f"{bucket_lines}")
        assert 'le="0.1"' in tagged[0]
        # The suffix parses as: value # {labels} ex_value ex_ts
        head, _, tail = tagged[0].partition(" # ")
        float(head.rsplit(" ", 1)[1])
        ex_value, ex_ts = tail.split("} ")[1].split(" ")
        assert float(ex_value) == pytest.approx(0.05)
        assert float(ex_ts) > 0

    def test_quantile_exemplar_finds_p99_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "x", buckets=(0.1, 1.0, 10.0))
        for i in range(50):
            h.observe(0.05, exemplar=f"fast{i}")
        for i in range(5):  # ~9% slow: the p99 rank lands here
            h.observe(5.0, exemplar=f"slow{i}")
        p99 = h.quantile_exemplar(0.99)
        assert p99["trace_id"] == "slow4"
        assert p99["le"] == "10"
        p50 = h.quantile_exemplar(0.50)
        assert p50["trace_id"] == "fast49"

    def test_quantile_falls_back_to_nearest_holding_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "x", buckets=(0.1, 1.0, 10.0))
        for _ in range(99):
            h.observe(0.05)           # no exemplars in p99's bucket
        h.observe(0.07, exemplar="only_tag")
        assert h.quantile_exemplar(0.99)["trace_id"] == "only_tag"

    def test_no_observations_is_none(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat", "x", buckets=(1.0,))
        assert h.quantile_exemplar(0.99) is None
        h.observe(0.5)  # observed, but never with an exemplar
        assert h.quantile_exemplar(0.99) is None

    def test_metrics_endpoint_negotiates_openmetrics(self):
        from pydcop_tpu.observability.metrics import registry
        from pydcop_tpu.observability.server import TelemetryServer

        registry.histogram(
            "neg_test_seconds", "x",
            buckets=(1.0,)).observe(0.5, exemplar="negotiate1")
        server = TelemetryServer(port=0).start()
        try:
            req = urllib.request.Request(
                server.url + "/metrics",
                headers={"Accept": "application/openmetrics-text"})
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert "openmetrics-text" in \
                    resp.headers["Content-Type"]
                om = resp.read().decode()
            assert 'negotiate1' in om
            assert om.rstrip().endswith("# EOF")
            with urllib.request.urlopen(server.url + "/metrics",
                                        timeout=10) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
                classic = resp.read().decode()
            assert " # {" not in classic, \
                "classic scrape must stay v0.0.4-parsable"
        finally:
            server.stop()

    def test_service_stats_expose_resolvable_exemplars(self):
        tracer.enable()
        svc = _service()
        svc.start()
        try:
            rid = svc.submit(_instance(8, 5), params=PARAMS)
            result = svc.result(rid, wait=60.0)
            tid = result["trace_id"]
            stats = svc.stats()
            events = tracer.events()
        finally:
            svc.stop(drain=False)
            tracer.disable()
        # The quantile face is populated (the histogram is process-
        # global, so WHICH request owns the p99 bucket depends on
        # suite history — serve_smoke asserts p99 ownership in a
        # fresh process).
        p99 = stats["latency_exemplars"]["p99"]
        assert p99 is not None and p99["trace_id"]
        # This request's observation left its exemplar in its native
        # bucket, one hop from the trace that resolves it.
        from pydcop_tpu.observability.metrics import registry
        hist = registry.histogram("pydcop_request_latency_seconds")
        snap = hist.snapshot()[0]["exemplars"]
        assert any(cell["trace_id"] == tid for cell in snap.values())
        tree = query_request(events, tid)
        assert tree["events"] > 0 and "engine_segment" in tree["names"]


# ------------------------------------------------------------------ #
# flight recorder + postmortem bundles


class TestFlightRecorder:
    def test_ring_records_while_file_tracing_off(self, tmp_path):
        t = Tracer()
        recorder = FlightRecorder(events=8,
                                  bundle_dir=str(tmp_path))
        t.set_flight(recorder)
        assert t.active and not t.enabled
        for i in range(20):
            t.instant("tick", "x", i=i)
        assert t.events() == [], \
            "disabled session tracer must not buffer"
        ring = recorder.snapshot()
        assert len(ring) == 8, "ring not bounded at its capacity"
        assert [e["args"]["i"] for e in ring] == list(range(12, 20))

    def test_flight_only_threads_do_not_accumulate_buffers(self):
        """Regression: with the always-on ring attached and file
        tracing OFF (the production serve default, one HTTP handler
        thread per request), short-lived threads must not leave
        permanent registrations in the tracer — that is an unbounded
        leak under sustained traffic."""
        t = Tracer()
        t.set_flight(FlightRecorder(events=64))

        def worker(i):
            t.instant("req", "x", i=i)

        for i in range(50):
            th = threading.Thread(target=worker, args=(i,))
            th.start()
            th.join()
        assert len(t._buffers) == 0, \
            f"{len(t._buffers)} flight-only threads leaked"
        assert len(t.flight.snapshot()) == 50
        # A session started afterwards still registers lanes.
        t.enable()
        t.instant("session", "x")
        assert len(t._buffers) == 1
        assert t.events()[0]["name"] == "session"

    def test_snapshot_safe_under_concurrent_appends(self, tmp_path):
        """A bundle cut while other threads record must never lose
        the event tail to 'deque mutated during iteration' — the
        anomaly fires exactly when the process is busiest."""
        recorder = FlightRecorder(events=256,
                                  bundle_dir=str(tmp_path))
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                recorder.record({"name": "ev", "args": {"i": i}})
                i += 1

        threads = [threading.Thread(target=hammer)
                   for _ in range(4)]
        for th in threads:
            th.start()
        try:
            for _ in range(200):
                snap = recorder.snapshot()
                assert len(snap) <= 256
        finally:
            stop.set()
            for th in threads:
                th.join(timeout=10)

    def test_bundle_retention_keeps_last_n(self, tmp_path):
        recorder = FlightRecorder(events=8,
                                  bundle_dir=str(tmp_path), keep=3)
        paths = [recorder.bundle("kind_a") for _ in range(5)]
        left = sorted(glob.glob(str(tmp_path / "bundle_*.json")))
        assert len(left) == 3
        assert set(left) == set(paths[-3:]), \
            "retention must evict oldest-first"

    def test_detached_recorder_restores_zero_overhead_gate(self):
        t = Tracer()
        t.set_flight(FlightRecorder(events=4))
        t.set_flight(None)
        assert not t.active
        t.instant("dropped", "x")
        assert t.events() == []

    def test_trigger_bundle_tail_contains_anomaly_instant(
            self, flight_ring):
        tracer.instant("before", "x", n=1)
        path = flight_ring.trigger("guard_trip", kind_detail="nan",
                                   cycle=14)
        assert path and os.path.exists(path)
        doc = json.load(open(path, encoding="utf-8"))
        assert doc["kind"] == "guard_trip"
        tail = doc["events"]
        assert tail[-1]["name"] == "anomaly"
        assert tail[-1]["args"]["kind"] == "guard_trip"
        assert any(e["name"] == "before" for e in tail), \
            "pre-anomaly context missing from the ring tail"
        # Diagnostics sections all present.
        for section in ("metrics", "healthz", "env",
                        "probe_diagnostics"):
            assert section in doc, f"bundle missing {section}"
        assert doc["pid"] == os.getpid()

    def test_trigger_storm_rate_limited_but_force_wins(
            self, flight_ring):
        first = flight_ring.trigger("guard_trip")
        second = flight_ring.trigger("guard_trip")
        assert first is not None and second is None
        assert flight_ring.suppressed == 1
        forced = flight_ring.trigger("recovery_exhausted",
                                     force=True)
        assert forced is not None and forced != first

    def test_journal_provider_folds_into_bundle(self, flight_ring):
        set_journal_provider(
            lambda: {"pending_replayable": 3, "journal_bytes": 512})
        try:
            doc = flight_ring.make_bundle("on_demand")
        finally:
            set_journal_provider(None)
        assert doc["journal"]["pending_replayable"] == 3
        assert "journal" not in flight_ring.make_bundle("on_demand")

    def test_provider_clear_is_identity_guarded(self, flight_ring):
        """A stopping service must not strip a sibling's journal
        registration from future bundles."""
        from pydcop_tpu.observability.flight import (
            clear_journal_provider,
        )

        def service_a():
            return {"pending_replayable": 1}

        def service_b():
            return {"pending_replayable": 2}

        set_journal_provider(service_a)
        try:
            set_journal_provider(service_b)  # B takes over
            clear_journal_provider(service_a)  # A stops late
            doc = flight_ring.make_bundle("on_demand")
            assert doc["journal"]["pending_replayable"] == 2, \
                "A's late clear wiped B's registration"
            clear_journal_provider(service_b)
            assert "journal" not in flight_ring.make_bundle(
                "on_demand")
        finally:
            set_journal_provider(None)

    def test_sibling_service_stop_keeps_survivor_provider(
            self, flight_ring, tmp_path):
        """The SolveService wiring end-to-end: stop a second
        journaled service while the first still runs — the first's
        backlog still reaches bundles."""
        a = _service(journal_dir=str(tmp_path / "a")).start()
        b = _service(journal_dir=str(tmp_path / "b")).start()
        try:
            b.stop(drain=False)
            # B registered last (last-writer-wins) and cleared its
            # own registration on stop: no stale provider remains.
            doc = flight_ring.make_bundle("on_demand")
            assert doc.get("journal", {}).get("dir") != str(
                tmp_path / "b"), "stopped service left its provider"
        finally:
            a.stop(drain=False)

    @pytest.mark.parametrize("value,expect", [
        ("0", None), ("off", None), ("false", None), ("no", None),
        ("none", None), ("disabled", None), ("-3", None),
        ("1", 2048), ("garbage", 2048),
        ("4096", 4096),
    ])
    def test_ring_size_env_parsing(self, value, expect):
        assert ring_size_from_env(value) == expect


class TestAnomalyPostmortem:
    """The ISSUE 9 anomaly acceptance, battery form: injected
    failures produce bundles on disk whose tail holds the trigger."""

    def test_guard_trip_dumps_bundle_with_trigger_in_tail(
            self, flight_ring):
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.resilience.recovery import RecoveryPolicy

        assert not tracer.enabled, \
            "this scenario proves the black box works with file " \
            "tracing OFF"
        dcop = _instance(8, 6)
        res = build_engine(dcop, {}).run_checkpointed(
            max_cycles=120, segment_cycles=7,
            recovery=RecoveryPolicy(trip_cycles=(14,),
                                    noise_seed=1))
        assert res.metrics["guard_trips"] == 1
        bundles = glob.glob(os.path.join(
            flight_ring.bundle_dir, "bundle_guard_trip_*.json"))
        assert len(bundles) == 1, bundles
        doc = json.load(open(bundles[0], encoding="utf-8"))
        anomalies = [e for e in doc["events"]
                     if e["name"] == "anomaly"]
        assert anomalies, "triggering instant missing from tail"
        assert anomalies[-1]["args"]["kind"] == "guard_trip"
        assert anomalies[-1]["args"]["cycle"] == 14
        # The ring held engine context from BEFORE the anomaly even
        # though no trace file was open.
        assert any(e["name"] == "engine_segment"
                   for e in doc["events"]), \
            "pre-anomaly engine spans missing from the black box"

    def test_poison_bin_isolation_dumps_bundle(self, flight_ring):
        svc = _service(batch_window_s=0.2)
        svc.start()
        real = svc._run_batch
        poison = set()

        def poisoned(reqs, params):
            if any(r.id in poison for r in reqs):
                raise RuntimeError("poison")
            return real(reqs, params)

        svc._run_batch = poisoned
        try:
            rids = [svc.submit(_instance(8, 10 + i), params=PARAMS)
                    for i in range(4)]
            poison.add(rids[1])
            for rid in rids:
                assert svc.result(rid, wait=60.0) is not None
        finally:
            svc.stop(drain=False)
        bundles = glob.glob(os.path.join(
            flight_ring.bundle_dir, "bundle_poison_bin_*.json"))
        assert bundles, "poison-bin isolation cut no bundle"
        doc = json.load(open(bundles[0], encoding="utf-8"))
        trigger = [e for e in doc["events"]
                   if e["name"] == "anomaly"
                   and e["args"]["kind"] == "poison_bin"]
        assert trigger, "poison_bin instant missing from tail"
        assert trigger[-1]["args"]["request"] == rids[1]
        assert trigger[-1]["args"]["retry_depth"] > 0


class TestDebugBundleCommand:
    def test_cli_cuts_local_bundle(self, flight_ring, tmp_path,
                                    capsys):
        from pydcop_tpu.dcop_cli import main as cli_main

        out = str(tmp_path / "ondemand.json")
        rc = cli_main(["debug", "bundle", "--out", out])
        assert rc == 0
        doc = json.load(open(out, encoding="utf-8"))
        assert doc["kind"] == "on_demand"
        assert doc["info"]["via"] == "cli"
        assert out in capsys.readouterr().out

    def test_http_debug_bundle_roundtrip(self, flight_ring,
                                          tmp_path, capsys):
        from pydcop_tpu.dcop_cli import main as cli_main
        from pydcop_tpu.observability.server import TelemetryServer

        server = TelemetryServer(port=0).start()
        try:
            tracer.instant("served", "x")
            with urllib.request.urlopen(
                    server.url + "/debug/bundle", timeout=10) as r:
                doc = json.loads(r.read())
            assert doc["kind"] == "on_demand"
            assert doc["info"]["via"] == "http"
            assert os.path.exists(doc["path"])
            out = str(tmp_path / "remote.json")
            rc = cli_main(["debug", "bundle", "--url", server.url,
                           "--out", out])
            assert rc == 0
            saved = json.load(open(out, encoding="utf-8"))
            assert saved["pid"] == os.getpid()
        finally:
            server.stop()

    def test_http_503_when_recorder_detached(self):
        from pydcop_tpu.observability.server import TelemetryServer

        prev = tracer.flight
        tracer.set_flight(None)
        server = TelemetryServer(port=0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    server.url + "/debug/bundle", timeout=10)
            assert err.value.code == 503
        finally:
            server.stop()
            tracer.set_flight(prev)


# ------------------------------------------------------------------ #
# serve-plane SSE lifecycle


class TestServeSSELifecycle:
    def test_client_sees_full_lifecycle_in_order(self):
        from pydcop_tpu.serving.http import ServeFrontEnd

        svc = _service(batch_window_s=0.2)
        svc.start()
        front = ServeFrontEnd(svc, port=0).start()
        seen = []
        connected = threading.Event()
        done = threading.Event()

        def listen():
            req = urllib.request.Request(front.url + "/events")
            with urllib.request.urlopen(req, timeout=30) as resp:
                connected.set()
                for raw in resp:
                    line = raw.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    event = json.loads(line[len("data: "):])
                    if event.get("event") == "request":
                        seen.append(event)
                        if event["phase"] in ("finished", "error"):
                            return

        listener = threading.Thread(target=listen, daemon=True)
        listener.start()
        assert connected.wait(10), "SSE stream never connected"
        try:
            body = json.dumps({
                "dcop": __import__(
                    "pydcop_tpu.dcop.yamldcop",
                    fromlist=["dcop_yaml"]).dcop_yaml(
                        _instance(8, 7)),
                "wait": True, "timeout": 60, "params": PARAMS,
            }).encode()
            req = urllib.request.Request(
                front.url + "/solve", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                result = json.loads(resp.read())
            assert result["status"] == "FINISHED"
            listener.join(timeout=30)
            assert not listener.is_alive(), \
                "lifecycle stream never delivered a terminal phase"
        finally:
            done.set()
            front.stop()
            svc.stop(drain=False)
        phases = [e["phase"] for e in seen
                  if e["trace_id"] == result["trace_id"]]
        assert phases == ["accepted", "dispatched", "finished"], \
            f"lifecycle out of order: {phases} (all: {seen})"
        assert all(e["id"] == result["id"] for e in seen
                   if e["trace_id"] == result["trace_id"])


# ------------------------------------------------------------------ #
# /healthz journal backlog


class TestHealthzJournalBacklog:
    def test_journaled_service_reports_replay_debt(self, tmp_path):
        svc = _service(journal_dir=str(tmp_path / "jnl"))
        svc.start()
        try:
            health = svc.health_summary()
            assert health["journal"]["active"]
            assert health["journal"]["pending_replayable"] == 0
            rid = svc.submit(_instance(8, 8), params=PARAMS)
            assert svc.result(rid, wait=60.0) is not None
            health = svc.health_summary()
            assert health["journal"]["pending_replayable"] == 0
            assert health["journal"]["journal_bytes"] > 0, \
                "accepted+completed records must show on-disk size"
        finally:
            svc.stop(drain=False)

    def test_pending_request_counts_as_replayable(self, tmp_path):
        svc = _service(journal_dir=str(tmp_path / "jnl"),
                       batch_window_s=5.0)  # park it in the queue
        svc.start()
        try:
            svc.submit(_instance(8, 9), params=PARAMS)
            assert svc.health_summary()["journal"][
                "pending_replayable"] == 1
        finally:
            svc.stop(drain=False)

    def test_journalless_service_has_no_journal_field(self):
        svc = _service()
        svc.start()
        try:
            assert "journal" not in svc.health_summary()
        finally:
            svc.stop(drain=False)

    def test_http_healthz_carries_backlog(self, tmp_path):
        from pydcop_tpu.serving.http import ServeFrontEnd

        svc = _service(journal_dir=str(tmp_path / "jnl"))
        svc.start()
        front = ServeFrontEnd(svc, port=0).start()
        try:
            with urllib.request.urlopen(front.url + "/healthz",
                                        timeout=10) as resp:
                health = json.loads(resp.read())
            journal = health["journal"]
            assert journal["pending_replayable"] == 0
            assert "journal_bytes" in journal
            assert health["serving"]["breaker_state"] == "closed"
            assert health["status"] == "ok"
        finally:
            front.stop()
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# TraceFileError regressions (satellite: clean errors, not KeyError)


class TestTraceFileErrors:
    def _good_trace(self, path, anchor=1000.0):
        rows = [
            {HEADER_KEY: {"anchor_unix_us": anchor,
                          "anchor_perf_us": 10.0,
                          "host": "h", "pid": 1}},
            {"name": "s", "cat": "x", "ph": "X", "ts": 20.0,
             "dur": 5.0, "id": 1, "parent": 0, "tid": 1,
             "args": {}},
        ]
        with open(path, "w", encoding="utf-8") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        return path

    def test_truncated_header_line_names_the_file(self, tmp_path):
        path = str(tmp_path / "torn.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            f.write('{"%s": {"anchor_unix_us": 123' % HEADER_KEY)
        with pytest.raises(TraceFileError) as err:
            load_trace_file(path)
        assert "torn.jsonl" in str(err.value)
        assert "header" in str(err.value)

    def test_non_object_header_is_clean_error(self, tmp_path):
        path = str(tmp_path / "bad_header.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps({HEADER_KEY: 42}) + "\n")
        with pytest.raises(TraceFileError) as err:
            load_trace_file(path)
        assert "bad_header.jsonl" in str(err.value)

    def test_corrupt_anchor_fails_merge_cleanly(self, tmp_path):
        good = self._good_trace(str(tmp_path / "good.jsonl"))
        bad = str(tmp_path / "bad_anchor.jsonl")
        with open(bad, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {HEADER_KEY: {"anchor_unix_us": "garbage",
                              "anchor_perf_us": 10.0}}) + "\n")
            f.write(json.dumps(
                {"name": "s", "cat": "x", "ph": "X", "ts": 1.0,
                 "dur": 1.0, "id": 1, "parent": 0, "tid": 1,
                 "args": {}}) + "\n")
        out = str(tmp_path / "merged.json")
        with pytest.raises(TraceFileError) as err:
            merge_traces([good, bad], out)
        assert "bad_anchor.jsonl" in str(err.value)
        assert "anchor" in str(err.value)
        with pytest.raises(TraceFileError):
            load_events_aligned([good, bad])

    def test_nonfinite_anchor_is_corrupt_not_legacy(self, tmp_path):
        bad = self._good_trace(str(tmp_path / "nan.jsonl"),
                               anchor=float("nan"))
        good = self._good_trace(str(tmp_path / "good.jsonl"))
        with pytest.raises(TraceFileError) as err:
            merge_traces([good, bad], str(tmp_path / "out.json"))
        assert "nan.jsonl" in str(err.value)

    def test_headerless_file_still_loads_degraded(self, tmp_path):
        """A pre-PR-5 trace (no header at all) is legacy, not
        corrupt: loading degrades instead of raising."""
        path = str(tmp_path / "legacy.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(
                {"name": "s", "cat": "x", "ph": "X", "ts": 5.0,
                 "dur": 1.0, "id": 1, "parent": 0, "tid": 1,
                 "args": {}}) + "\n")
        assert len(load_trace_file(path)) == 1
        good = self._good_trace(str(tmp_path / "good.jsonl"))
        events = load_events_aligned([good, path])
        assert len(events) == 2


# ------------------------------------------------------------------ #
# bench-sentinel exemplar hygiene


class TestSentinelExemplar:
    def _sentinel(self):
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools"))
        import bench_sentinel

        return bench_sentinel

    def _write(self, root, serve_values, exemplars):
        for i, (sv, ex) in enumerate(zip(serve_values, exemplars)):
            doc = {"n": i, "parsed": {
                "value": 800.0, "backend": "cpu",
                "serve_problems_per_sec": sv,
                "exemplar_trace_id": ex,
            }}
            with open(os.path.join(
                    root, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump(doc, f)

    def test_regression_line_names_the_exemplar_trace(self,
                                                      tmp_path):
        sentinel = self._sentinel()
        d = str(tmp_path / "reg")
        os.makedirs(d)
        self._write(d, [50.0, 51.0, 49.0, 50.0, 10.0],
                    [None, None, None, None, "deadbeef01"])
        report = sentinel.run_check(d)
        assert report["failed"]
        assert report["series"]["serve:cpu"]["exemplar"] \
            == "deadbeef01"
        assert any("deadbeef01" in line
                   and "trace query --request" in line
                   for line in report["lines"]), report["lines"]

    def test_regression_without_exemplar_prints_no_pointer(
            self, tmp_path):
        sentinel = self._sentinel()
        d = str(tmp_path / "noex")
        os.makedirs(d)
        self._write(d, [50.0, 51.0, 49.0, 50.0, 10.0],
                    [None] * 5)
        report = sentinel.run_check(d)
        assert report["failed"]
        assert "exemplar" not in report["series"]["serve:cpu"]
        assert not any("trace query" in line
                       for line in report["lines"])

    def test_non_serve_regression_never_claims_the_exemplar(
            self, tmp_path):
        """The exemplar is the SERVING leg's p99 trace — a headline-
        bench regression must not point investigators at it."""
        sentinel = self._sentinel()
        d = str(tmp_path / "bench_reg")
        os.makedirs(d)
        for i, v in enumerate([800.0, 810.0, 790.0, 800.0, 100.0]):
            doc = {"n": i, "parsed": {
                "value": v, "backend": "cpu",
                "serve_problems_per_sec": 50.0,
                "exemplar_trace_id": "deadbeef01",
            }}
            with open(os.path.join(
                    d, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump(doc, f)
        report = sentinel.run_check(d)
        assert report["series"]["cpu"]["verdict"] == "regressed"
        assert report["series"]["serve:cpu"]["verdict"] == "ok"
        assert not any("trace query" in line
                       for line in report["lines"])

    def test_healthy_series_never_prints_exemplars(self, tmp_path):
        sentinel = self._sentinel()
        d = str(tmp_path / "ok")
        os.makedirs(d)
        self._write(d, [50.0, 51.0, 49.0, 50.0, 50.5],
                    ["a1", "a2", "a3", "a4", "a5"])
        report = sentinel.run_check(d)
        assert not report["failed"]
        assert not any("trace query" in line
                       for line in report["lines"])


# ------------------------------------------------------------------ #
# convergence-health telemetry


class TestConvergenceHealth:
    def test_probe_collects_residual_and_flip_rate(self):
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.observability.engine_probe import EngineProbe

        engine = build_engine(_instance(8, 11), {})
        reg = MetricsRegistry()
        probe = EngineProbe(engine, registry=reg)
        sse_events = []
        probe.snapshotter.add_listener(sse_events.append)
        res = engine.run_checkpointed(
            max_cycles=60, segment_cycles=10, probe=probe,
            stop_on_convergence=False)
        assert len(probe.convergence) == res.metrics["segments"]
        first_cycle, first_res, first_flips = probe.convergence[0]
        assert first_res is None and first_flips is None, \
            "first segment has no previous segment to diff against"
        curve = probe.convergence_curve()
        assert curve, "no convergence points after segment 1"
        for cycle, residual, flips in curve:
            assert residual >= 0.0 and 0.0 <= flips <= 1.0
        # Damped max-sum settles: the last flip rate must be 0 once
        # the run has converged to a fixpoint-stable assignment.
        assert curve[-1][2] == 0.0
        # Gauges carry the latest values.
        assert reg.value("pydcop_msg_residual") == pytest.approx(
            curve[-1][1])
        assert reg.value("pydcop_flip_rate") == pytest.approx(
            curve[-1][2])
        # The SSE payload (per-chunk snapshot events) carries them.
        tagged = [e for e in sse_events if "residual" in e]
        assert tagged and all("flip_rate" in e for e in tagged)

    def test_solve_result_carries_convergence_curve(self, tmp_path):
        from pydcop_tpu.api import solve

        res = solve(_instance(6, 12), "maxsum", backend="device",
                    max_cycles=60,
                    metrics_file=str(tmp_path / "m.jsonl"),
                    metrics_every=10)
        curve = res["metrics"]["convergence_curve"]
        assert curve and all(len(point) == 3 for point in curve)

"""Live session migration: move a warm solve session between replicas.

The reference pyDCOP's headline resilience feature is that
*computations migrate*: on agent failure its orchestration layer
re-homes replicated computations onto surviving agents.  The serve
plane's analogue moves a WHOLE warm session — engine message state,
problem, event history position — from one fleet replica to another,
reusing the PR-13 replay machinery verbatim for the rebuild (restore
equals uninterrupted is already proven by scenario_session_replay).

**The bundle.**  One JSON document carries everything a target needs
to rebuild the session exactly as :meth:`SessionManager.recover`
would after a crash:

- ``dcop`` — the session's problem as dcop yaml.  Preferably REBASED:
  the engine's *current* factor graph serialized back to yaml
  (:func:`engine_dcop_yaml` — open problem + every applied event
  batch), so the target rebuilds structurally from one document and
  zero event replays.  When a live factor can't round-trip through
  yaml, the bundle falls back to the open-record problem plus the
  journaled event batches (``rebased: false``).
- ``npz_b64`` / ``npz_path`` — the drain-checkpoint engine NPZ (warm
  message state at ``ckpt_seq``); base64 over the wire for live
  migration, a filesystem path for same-box dead-replica adoption.
- ``seq`` / ``ckpt_seq`` / ``cycle`` — the event-order position the
  target continues from.

**The protocol** (:func:`migrate_session`, driven by the router):

1. ``POST /admin/export_session`` on the source — the scheduler
   thread drains the session (every acked batch applied), checkpoints
   it, freezes it MIGRATING (new PATCHes 409 until the move
   resolves) and returns the bundle;
2. ``POST /admin/import_session`` on the target — rebuild via the
   recovery path, journal the session into the target's own segment
   (the import ack is as durable as an open's 201);
3. the router atomically repoints the session pin;
4. ``POST /admin/retire_session`` on the source — journal a MIGRATED
   close (the source's --recover must not resurrect what the target
   now owns), retire the checkpoint, end the SSE streams (clients
   reconnect through the router and land on the target).

On import failure the source is resumed (``/admin/resume_session``)
— the session never has zero owners.  Dead-replica adoption
(:func:`adopt_dead_sessions`) builds the same bundles straight from
the dead segment's compacted journal instead of step 1, because there
is no live source to export from.

Durability guarantee: every acked PATCH is either inside the bundle
(applied before the drain checkpoint) or journaled on whichever side
acked it — a client holding durable 200s and an open SSE stream
observes at most a reconnect and a 409-retry window, never a lost
acked event.  docs/serving.md "Elastic fleet".
"""

import base64
import binascii
import json
import logging
import os
import tempfile
import uuid
from typing import Any, Dict, List, Optional

from pydcop_tpu.observability import fleettrace
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.serving import journal as journal_mod

logger = logging.getLogger("pydcop.serving.migration")

BUNDLE_VERSION = 1


def engine_dcop_yaml(engine, name: str = "session") -> str:
    """Serialize a live DynamicMaxSumEngine's CURRENT problem back to
    dcop yaml — the rebase step for checkpoints and migration
    bundles.  Raises when any live factor can't round-trip (e.g. an
    expression constraint without its source expression); callers
    fall back to open-problem + event replay."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef
    from pydcop_tpu.dcop.yamldcop import dcop_yaml, load_dcop

    mode = engine.mode if engine.mode in ("min", "max") else "min"
    dcop = DCOP(name, objective=mode)
    for v in engine.variables:
        dcop.add_variable(v)
    for c in engine.factors.values():
        dcop.add_constraint(c)
    agents = sorted(engine.agents) or ["a0"]
    dcop.add_agents([AgentDef(a) for a in agents])
    out = dcop_yaml(dcop)
    # Round-trip proof: a yaml that fails to load again would turn a
    # fast checkpoint into a poisoned recovery.  Cheap relative to
    # the engine checkpoint that accompanies it.
    load_dcop(out)
    return out


def build_bundle(session_id: str, trace_id: str, dcop_yaml: str,
                 rebased: bool, params: Dict[str, Any], seq: int,
                 cycle: int,
                 events: Optional[List[Dict[str, Any]]] = None,
                 npz_bytes: Optional[bytes] = None,
                 ckpt_seq: Optional[int] = None,
                 npz_path: Optional[str] = None,
                 epoch: int = 1) -> Dict[str, Any]:
    bundle: Dict[str, Any] = {
        "version": BUNDLE_VERSION,
        "session_id": session_id,
        "trace_id": trace_id,
        "dcop": dcop_yaml,
        "rebased": bool(rebased),
        "params": dict(params or {}),
        "seq": int(seq),
        "cycle": int(cycle),
        "epoch": max(int(epoch), 1),
        "events": [
            {"seq": int(r.get("seq", 0)),
             "events": r.get("events") or [],
             **({"trace_id": r["trace_id"]}
                if r.get("trace_id") else {})}
            for r in (events or [])
        ],
    }
    if npz_bytes is not None:
        bundle["npz_b64"] = base64.b64encode(npz_bytes).decode()
    if npz_path is not None:
        bundle["npz_path"] = npz_path
    if ckpt_seq is not None:
        bundle["ckpt_seq"] = int(ckpt_seq)
    return bundle


def _bundle_npz_bytes(bundle: Dict[str, Any]) -> Optional[bytes]:
    b64 = bundle.get("npz_b64")
    if b64:
        try:
            return base64.b64decode(b64)
        except (binascii.Error, ValueError) as exc:
            raise ValueError(f"bad npz_b64 in bundle: {exc}")
    path = bundle.get("npz_path")
    if path:
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError as exc:
            # Same-box adoption race (the checkpoint was retired
            # under us): degrade to a cold rebuild, exactly like a
            # bad snapshot during --recover.
            logger.warning("bundle npz_path %s unreadable (%s); "
                           "importing cold", path, exc)
    return None


def install_bundle(manager, bundle: Dict[str, Any]):
    """Target-side import: rebuild the session through the SAME
    recovery path a --recover restart uses, journal it into this
    service's own segment, and enqueue its first re-convergence
    segment.  Returns the installed SolveSession.  Runs on a
    submitting thread (like ``SessionManager.open``)."""
    from pydcop_tpu.dcop.yamldcop import load_dcop

    if bundle.get("version") != BUNDLE_VERSION:
        raise ValueError(
            f"unsupported bundle version {bundle.get('version')!r}")
    sid = bundle.get("session_id")
    if not sid or not isinstance(sid, str):
        raise ValueError("bundle needs a 'session_id'")
    dcop_src = bundle.get("dcop")
    if not isinstance(dcop_src, str) or not dcop_src.strip():
        raise ValueError("bundle needs a 'dcop' yaml string")
    with manager._lock:
        existing = manager._sessions.get(sid)
        if existing is not None and existing.status == "OPEN":
            raise ValueError(f"session {sid!r} already live here")
    trace_id = bundle.get("trace_id") or ""
    params = bundle.get("params") or {}
    seq = int(bundle.get("seq") or 0)
    npz = _bundle_npz_bytes(bundle)
    ckpt_seq = bundle.get("ckpt_seq")

    # Land the NPZ next to this service's journal (tmp+rename) so a
    # later checkpoint of the imported session overwrites it in
    # place; a journal-less service parks it in tmpdir.
    npz_dest = None
    if npz is not None and ckpt_seq is not None:
        dest_dir = manager.service.journal_dir or tempfile.gettempdir()
        os.makedirs(dest_dir, exist_ok=True)
        npz_dest = os.path.join(dest_dir, f"session_{sid}.npz")
        tmp = npz_dest + ".tmp.npz"
        with open(tmp, "wb") as f:
            f.write(npz)
        os.replace(tmp, npz_dest)

    open_rec = journal_mod.session_open_record(
        sid, dcop_src, params, trace_id=trace_id or None,
        epoch=int(bundle.get("epoch") or 1))
    event_recs = [
        journal_mod.session_event_record(
            sid, r.get("seq", 0), r.get("events") or [],
            trace_id=r.get("trace_id"))
        for r in (bundle.get("events") or [])
    ]
    ckpt_rec = None
    if npz_dest is not None:
        ckpt_rec = journal_mod.session_ckpt_record(
            sid, int(ckpt_seq), npz_dest,
            cycle=int(bundle.get("cycle") or 0),
            dcop=dcop_src if bundle.get("rebased") else None)

    # Durability FIRST, like open(): the records reach this segment's
    # journal before the rebuild, so a crash mid-import replays the
    # session here (the source has not retired it yet — worst case
    # both sides replay and the router pin decides the owner).
    journal = manager.service._journal
    if journal is not None:
        journal.append(open_rec)
        for rec in event_recs:
            journal.append(rec)
        if ckpt_rec is not None:
            journal.append(ckpt_rec)

    sess = manager._recover_one(load_dcop, open_rec, ckpt_rec,
                                event_recs)
    # The event-order position continues from the source: a rebased
    # bundle carries no event records, so _recover_one's max-seq scan
    # alone would restart the order at zero.
    with manager._lock:
        sess.seq = max(seq, sess.seq)
        sess.applied_seq = sess.seq
    manager.migrated_in += 1
    logger.info("session %s imported (seq %d%s)", sid, sess.seq,
                ", rebased" if bundle.get("rebased") else "")
    return sess


# --------------------------------------------------------------------- #
# Router-side orchestration


def migrate_session(router, session_id: str,
                    target_index: Optional[int] = None,
                    timeout: float = 120.0) -> Dict[str, Any]:
    """Move one session between replicas (operator ``POST
    /admin/migrate``, scale-down drain).  Export → import → repoint
    pin → retire; on import failure the source session is resumed.
    Raises KeyError for an unpinned session, RuntimeError when a step
    fails unrecoverably."""
    source = router.pinned(session_id, router._session_pins)
    if source is None:
        raise KeyError(session_id)
    target = None
    if target_index is not None:
        if not 0 <= target_index < len(router.replicas):
            raise ValueError(f"no replica {target_index}")
        target = router.replicas[target_index]
        if target.status != "up":
            raise RuntimeError(
                f"target replica {target_index} is {target.status}")
    else:
        live = [r for r in router.candidates()
                if r.index != source.index]
        if not live:
            raise RuntimeError("no live target replica to migrate to")
        target = min(live, key=lambda r: r.in_flight)
    if target.index == source.index:
        raise ValueError("target is the session's current replica")

    # The whole export→import→retire hop rides ONE trace context —
    # the session's own when the router remembers it, a fresh one
    # otherwise — so forensics shows the migration inside the
    # session's causal tree.
    ctx = fleettrace.TraceContext(
        router.trace_for(session_id) or uuid.uuid4().hex[:16])
    status, _ctype, body = router._forward(
        source, "POST", "/admin/export_session",
        json.dumps({"session_id": session_id,
                    "wait": timeout}).encode(),
        timeout=timeout + 30.0, trace=ctx)
    if status != 200:
        raise RuntimeError(
            f"export failed on replica {source.index} ({status}): "
            f"{body[:300]!r}")
    bundle = json.loads(body)
    # Ownership epoch bumps ON THE MOVE (ISSUE 19): the target's copy
    # carries the new epoch, the router stamps it on every forwarded
    # PATCH, and any write still addressed to the source's epoch is
    # rejected as stale — split-brain fencing, not best-effort retire.
    new_epoch = router.bump_epoch(session_id)
    bundle["epoch"] = new_epoch

    try:
        status, _ctype, body = router._forward(
            target, "POST", "/admin/import_session",
            json.dumps(bundle).encode(), timeout=timeout + 30.0,
            trace=ctx)
        if status != 201:
            raise RuntimeError(
                f"import failed on replica {target.index} "
                f"({status}): {body[:300]!r}")
    except (OSError, RuntimeError):
        # The session must never have zero owners: un-freeze the
        # source before surfacing the failure.
        try:
            router._forward(
                source, "POST", "/admin/resume_session",
                json.dumps({"session_id": session_id}).encode(),
                timeout=30.0, trace=ctx)
        except OSError:
            logger.warning("session %s: import failed AND source "
                           "resume unreachable — the source journal "
                           "still owns it", session_id)
        raise

    router.pin(session_id, target, router._session_pins)
    try:
        router._forward(
            source, "POST", "/admin/retire_session",
            json.dumps({"session_id": session_id,
                        "moved_to": target.url}).encode(),
            timeout=30.0, trace=ctx)
    except OSError:
        # The target owns the session (pin repointed + epoch bumped);
        # an unretired source copy is fenced when the source heals —
        # arm the fence now so the next successful probe flushes it.
        router.record_fence(source.index, session_id, new_epoch)
        logger.warning("session %s: retire on replica %d "
                       "unreachable; fence armed at epoch %d",
                       session_id, source.index, new_epoch)
    with router._lock:
        router.migrations += 1
    if tracer.active:
        tracer.instant("router_migrate", "fleet",
                       trace_id=ctx.trace_id, session=session_id,
                       source=source.index, target=target.index)
    logger.info("session %s migrated: replica %d -> %d",
                session_id, source.index, target.index)
    return {"session_id": session_id, "from": source.index,
            "to": target.index}


def adopt_dead_sessions(router, dead) -> int:
    """Dead-replica failover: compact the dead segment's journal,
    build a same-box bundle per open session (checkpoint referenced
    by path — the survivors share the filesystem), import each into
    the least-loaded survivor, journal a MIGRATED close into the dead
    segment so its restart does not resurrect what a survivor now
    owns, and repoint the session pins.  Returns the adopted count;
    sessions that fail to import stay in the dead segment for the
    restart-in-place replay."""
    if not dead.journal_dir:
        return 0
    try:
        _pending, sessions, _results = journal_mod.compact_journal(
            dead.journal_dir)
    except OSError as exc:
        logger.warning("replica %d: dead-segment compaction failed "
                       "(%s); restart replays the full segment",
                       dead.index, exc)
        return 0
    if not sessions:
        return 0
    adopted = 0
    for rec in sessions:
        open_rec = rec["open"]
        ckpt = rec.get("ckpt") or {}
        sid = open_rec.get("id")
        live = [r for r in router.candidates()
                if r.index != dead.index]
        if not live:
            break
        target = min(live, key=lambda r: r.in_flight)
        seqs = [r.get("seq", 0) for r in rec.get("events") or []]
        seq = max([ckpt.get("seq", 0)] + seqs)
        # Adoption is a forced move: bump the ownership epoch past
        # whatever the (possibly merely partitioned) dead replica
        # journaled, so a healed original cannot double-apply.
        new_epoch = router.bump_epoch(
            sid, floor=int(open_rec.get("epoch") or 1) + 1)
        bundle = build_bundle(
            sid, open_rec.get("trace_id") or "",
            ckpt.get("dcop") or open_rec["dcop"],
            rebased=bool(ckpt.get("dcop")),
            params=open_rec.get("params") or {},
            seq=seq, cycle=int(ckpt.get("cycle") or 0),
            events=rec.get("events"),
            npz_path=ckpt.get("path"),
            ckpt_seq=(ckpt.get("seq")
                      if ckpt.get("path") else None),
            epoch=new_epoch)
        ctx = fleettrace.TraceContext(
            router.trace_for(sid) or open_rec.get("trace_id")
            or uuid.uuid4().hex[:16])
        try:
            status, _ctype, body = router._forward(
                target, "POST", "/admin/import_session",
                json.dumps(bundle).encode(), timeout=120.0,
                trace=ctx)
            if status != 201:
                raise RuntimeError(
                    f"import answered {status}: {body[:200]!r}")
        except (OSError, RuntimeError, ValueError) as exc:
            logger.warning(
                "session %s: adoption by replica %d failed (%s); "
                "left for the dead replica's restart replay",
                sid, target.index, exc)
            continue
        # The dead segment must forget the session BEFORE its slot
        # restarts with --recover.
        journal_mod.append_record(
            dead.journal_dir,
            journal_mod.session_close_record(sid, "MIGRATED"))
        router.pin(sid, target, router._session_pins)
        # The close record covers a restart-in-place; a replica that
        # was merely PARTITIONED never restarts, so arm a fence that
        # flushes the moment it answers the prober again.
        router.record_fence(dead.index, sid, new_epoch)
        adopted += 1
        with router._lock:
            router.migrations += 1
        if tracer.active:
            tracer.instant("router_migrate", "fleet",
                           trace_id=ctx.trace_id, session=sid,
                           source=dead.index, target=target.index,
                           adopted=True)
        logger.info("session %s adopted by replica %d after replica "
                    "%d death", sid, target.index, dead.index)
    return adopted

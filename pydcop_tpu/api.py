"""High-level solve API.

Reference parity: pydcop/infrastructure/run.py:52 ``solve()`` — build
graph → distribute → run → return assignment.  Here the default backend
is the device engine (one jitted BSP program); ``backend="thread"`` runs
the agent-mode runtime for reference-equivalent distributed execution.
"""

import time
from typing import Any, Dict, Optional, Union

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.dcop.dcop import DCOP


class SolveResult(dict):
    """Dict-like result: assignment, cost, violations, cycles, times."""

    @property
    def assignment(self) -> Dict[str, Any]:
        return self["assignment"]

    @property
    def cost(self) -> float:
        return self["cost"]


def solve(dcop: DCOP, algo_def: Union[str, AlgorithmDef],
          distribution: str = "oneagent",
          backend: str = "device",
          timeout: Optional[float] = None,
          max_cycles: int = 1000,
          algo_params: Optional[Dict[str, Any]] = None,
          mesh=None, n_devices: Optional[int] = None,
          warmup: bool = False,
          ui_port: Optional[int] = None,
          collector=None,
          collect_moment: str = "value_change",
          collect_period: float = 1.0,
          delay: Optional[float] = None,
          checkpoint_dir: Optional[str] = None,
          checkpoint_every: Optional[int] = None,
          resume: bool = False,
          fault_plan=None,
          ) -> SolveResult:
    """Solve a DCOP and return assignment + quality metrics.

    backend="device": batched engine on TPU/CPU devices (default).
    backend="thread": agent-mode runtime (threads + in-process messages),
    reference-equivalent semantics.

    Resilience knobs (docs/resilience.md): ``checkpoint_dir`` chunks a
    device-mode solve into ``checkpoint_every``-cycle segments with an
    NPZ state snapshot between segments; ``resume=True`` continues
    from the newest snapshot in that directory instead of cycle 0
    (identical final result — the battery asserts it).  ``fault_plan``
    (a resilience.faults.FaultPlan) runs the thread backend under
    seeded message faults and crash injection.
    warmup=True runs the compiled program once untimed before the timed
    call, so one-shot solves report steady-state rates instead of
    compile-dominated ones (device backend only).  The warm-up run is a
    FULL discarded solve (the cycle count is baked into the compiled
    program, so a shorter variant would compile a different
    executable): expect ~2x wall time for large max_cycles, and prefer
    warmup=False when only the answer matters.  Host-driven sweep
    algorithms (dpop, syncbb, ncbb) and maxsum decimation ignore it —
    their runners already report compile time separately.

    Example::

        >>> from pydcop_tpu.dcop.dcop import DCOP
        >>> from pydcop_tpu.dcop.objects import Domain, Variable
        >>> from pydcop_tpu.dcop.relations import constraint_from_str
        >>> d = Domain('d', '', [0, 1])
        >>> x, y = Variable('x', d), Variable('y', d)
        >>> dcop = DCOP('doc', objective='min')
        >>> dcop.add_constraint(
        ...     constraint_from_str('c', '(x + y - 1)**2', [x, y]))
        >>> res = solve(dcop, 'dpop')
        >>> res['status'], round(res['cost'], 3)
        ('FINISHED', 0.0)
    """
    if isinstance(algo_def, str):
        algo_def = AlgorithmDef.build_with_default_param(
            algo_def, algo_params or {}, mode=dcop.objective
        )
    module = load_algorithm_module(algo_def.algo)

    # Resilience knobs are backend-specific: reject silently-ignored
    # combinations instead of letting a chaos test believe faults were
    # injected (or a preemptible run believe it checkpointed).
    if fault_plan is not None and backend == "device":
        raise ValueError(
            "fault_plan wraps agent transports: use backend='thread'"
        )
    if (checkpoint_dir is not None or resume) and backend != "device":
        raise ValueError(
            "checkpointing segments the device engine's solve loop: "
            "use backend='device'"
        )
    if resume and checkpoint_dir is None:
        raise ValueError(
            "resume=True needs checkpoint_dir: there is no snapshot "
            "location to resume from"
        )

    if backend == "device":
        if not hasattr(module, "solve_on_device"):
            raise NotImplementedError(
                f"Algorithm {algo_def.algo} has no device path; use "
                "backend='thread'"
            )
        # Join the cross-host runtime when configured (PYDCOP_* env
        # vars / PYDCOP_MULTIHOST=auto); single-host runs no-op.
        from pydcop_tpu.engine.multihost import initialize_multihost

        initialize_multihost()
        t0 = time.perf_counter()
        if checkpoint_dir is not None:
            if not hasattr(module, "build_engine"):
                raise NotImplementedError(
                    f"Algorithm {algo_def.algo} has no segmentable "
                    "engine: checkpointing supports maxsum-family "
                    "solves"
                )
            from pydcop_tpu.resilience.checkpoint import (
                CheckpointManager,
                resume_from_checkpoint,
            )

            engine = module.build_engine(
                dcop, algo_def.params, mesh=mesh, n_devices=n_devices
            )
            manager = CheckpointManager(
                checkpoint_dir, every=checkpoint_every or 100
            )
            if resume:
                res = resume_from_checkpoint(
                    engine, manager, max_cycles=max_cycles
                )
            else:
                res = engine.run_checkpointed(
                    max_cycles=max_cycles, manager=manager
                )
        else:
            res = module.solve_on_device(
                dcop, algo_def, max_cycles=max_cycles, mesh=mesh,
                n_devices=n_devices, warmup=warmup,
            )
        cost, violations = dcop.solution_cost(res.assignment)
        return SolveResult(
            status="FINISHED" if res.converged else "TIMEOUT",
            assignment=res.assignment,
            cost=cost,
            violations=violations,
            cycles=res.cycles,
            time=res.time_s,
            compile_time=res.compile_time_s,
            total_time=time.perf_counter() - t0,
            metrics=res.metrics,
            backend="device",
        )

    if backend in ("thread", "process"):
        from pydcop_tpu.infrastructure.agent_algorithms import (
            has_agent_computation,
        )
        from pydcop_tpu.infrastructure.run import solve_with_agents

        # Reject before deployment rather than crashing mid-run on the
        # first build_computation call.
        if not has_agent_computation(algo_def.algo):
            raise NotImplementedError(
                f"Algorithm {algo_def.algo!r} has no agent-mode "
                "computation yet; use backend='device'"
            )

        # Bound non-terminating algorithms: without an explicit timeout a
        # maxsum/dsa run would block forever on the finished event.
        if timeout is None:
            timeout = 15.0
        return solve_with_agents(
            dcop, algo_def, distribution=distribution,
            timeout=timeout, max_cycles=max_cycles, mode=backend,
            ui_port=ui_port, collector=collector,
            collect_moment=collect_moment,
            collect_period=collect_period, delay=delay,
            fault_plan=fault_plan,
        )

    raise ValueError(f"Unknown backend {backend!r}")

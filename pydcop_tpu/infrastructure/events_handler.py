"""Scenario event processing for dynamic DCOPs.

Reference parity: pydcop/infrastructure/orchestrator.py:340 (_process_event
scheduling) and :955-1010 (_orchestrator_scenario_event: pause, apply
agent removals, trigger repair, resume).

Supports delay, add_agent and remove_agent events.  Removals trigger
repair-based migration of the orphaned computations through the
replication layer (orchestrator.py repair orchestration, both
device-central and distributed modes).  Unknown action types are logged
and skipped.
"""

import logging
import time

logger = logging.getLogger("pydcop.scenario")


def run_scenario_events(orchestrator, scenario):
    """Execute scenario events against a running orchestrator."""
    for event in scenario.events:
        if event.is_delay:
            time.sleep(event.delay)
            continue
        logger.info("Scenario event %s", event.id)
        membership_changed = any(
            a.type in ("add_agent", "remove_agent")
            for a in event.actions or []
        )
        orchestrator.pause_agents()
        for action in event.actions or []:
            if action.type == "remove_agent":
                agent = action.args.get("agent")
                logger.info("Scenario: removing agent %s", agent)
                orchestrator.remove_agent(agent)
            elif action.type == "add_agent":
                from pydcop_tpu.dcop.objects import AgentDef

                agent = action.args.get("agent")
                extras = {
                    k: v for k, v in action.args.items()
                    if k != "agent"
                }
                logger.info("Scenario: adding agent %s", agent)
                orchestrator.add_agent(AgentDef(agent, **extras))
            else:
                logger.warning(
                    "Unsupported scenario action %s (skipped)",
                    action.type,
                )
        # Heal replica counts after membership changes: replication is
        # idempotent (existing replica holders count toward k), so
        # re-triggering only places the missing replicas (reference
        # analogue: _replicate_on_agent_lost,
        # pydcop/replication/dist_ucs_hostingcosts.py:1067).
        if membership_changed and orchestrator.replication_k:
            orchestrator.start_replication(orchestrator.replication_k)
        orchestrator.resume_agents()

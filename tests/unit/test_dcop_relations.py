"""Unit tests for the constraint algebra (join/projection = DPOP math)."""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import (
    AsNAryFunctionRelation,
    ConditionalRelation,
    NAryFunctionRelation,
    NAryMatrixRelation,
    NeutralRelation,
    UnaryBooleanRelation,
    UnaryFunctionRelation,
    ZeroAryRelation,
    assignment_cost,
    assignment_matrix,
    constraint_from_str,
    find_arg_optimal,
    find_optimal,
    find_optimum,
    generate_assignment_as_dict,
    join,
    optimal_cost_value,
    projection,
)

d3 = Domain("d3", "", [0, 1, 2])
x = Variable("x", d3)
y = Variable("y", d3)
z = Variable("z", d3)


class TestBasicRelations:
    def test_zero_ary(self):
        r = ZeroAryRelation("r", 42)
        assert r() == 42
        assert r.arity == 0

    def test_unary_function(self):
        r = UnaryFunctionRelation("r", x, lambda v: v * 2)
        assert r(2) == 4
        assert r(x=2) == 4

    def test_unary_expression(self):
        r = UnaryFunctionRelation("r", x, "x + 1")
        assert r(x=1) == 2

    def test_unary_boolean(self):
        b = Variable("b", Domain("db", "", [True, False]))
        r = UnaryBooleanRelation("r", b)
        assert r(True) == 1
        assert r(False) == 0

    def test_nary_function(self):
        r = NAryFunctionRelation(lambda a, b: a + b, [x, y], name="sum")
        assert r(1, 2) == 3
        assert r(x=1, y=2) == 3
        assert r.arity == 2

    def test_nary_expression(self):
        r = constraint_from_str("r", "x * y + z", [x, y, z])
        assert r.scope_names == ["x", "y", "z"]
        assert r(x=2, y=2, z=1) == 5

    def test_decorator(self):
        @AsNAryFunctionRelation(x, y)
        def my_rel(x, y):
            return abs(x - y)

        assert my_rel.name == "my_rel"
        assert my_rel(0, 2) == 2

    def test_neutral(self):
        r = NeutralRelation([x, y])
        assert r(x=1, y=2) == 0
        assert np.all(r.to_array() == 0)

    def test_conditional(self):
        cond = UnaryFunctionRelation("cond", x, "x > 1")
        rel = UnaryFunctionRelation("rel", y, "y * 10")
        r = ConditionalRelation(cond, rel)
        assert r(x=2, y=1) == 10
        assert r(x=0, y=1) == 0

    def test_slice_function_relation(self):
        r = constraint_from_str("r", "x * 10 + y", [x, y])
        s = r.slice({"x": 2})
        assert s.scope_names == ["y"]
        assert s(y=1) == 21


class TestMatrixRelation:
    def test_build_and_call(self):
        m = np.arange(9).reshape(3, 3)
        r = NAryMatrixRelation([x, y], m, "r")
        assert r(x=1, y=2) == 5
        assert r.get_value_for_assignment([2, 0]) == 6

    def test_shape_check(self):
        with pytest.raises(ValueError):
            NAryMatrixRelation([x, y], np.zeros((2, 3)), "r")

    def test_set_value(self):
        r = NAryMatrixRelation([x, y], name="r")
        r2 = r.set_value_for_assignment({"x": 0, "y": 1}, 5)
        assert r2(x=0, y=1) == 5
        assert r(x=0, y=1) == 0  # immutable

    def test_slice(self):
        m = np.arange(9).reshape(3, 3)
        r = NAryMatrixRelation([x, y], m, "r")
        s = r.slice({"x": 1})
        assert s.scope_names == ["y"]
        assert s(y=0) == 3

    def test_from_func(self):
        f = constraint_from_str("r", "x + y", [x, y])
        r = NAryMatrixRelation.from_func_relation(f)
        assert r(x=2, y=2) == 4

    def test_simple_repr_roundtrip(self):
        from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

        m = np.arange(9).reshape(3, 3)
        r = NAryMatrixRelation([x, y], m, "r")
        r2 = from_repr(simple_repr(r))
        assert r2 == r


class TestJoinProjection:
    def test_join_shared_var(self):
        r1 = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r1", "x + y", [x, y]))
        r2 = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r2", "y * z", [y, z]))
        j = join(r1, r2)
        assert set(j.scope_names) == {"x", "y", "z"}
        assert j(x=1, y=2, z=2) == (1 + 2) + (2 * 2)

    def test_join_disjoint(self):
        r1 = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r1", "x", [x]))
        r2 = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r2", "z", [z]))
        j = join(r1, r2)
        assert j(x=1, z=2) == 3

    def test_projection_min(self):
        r = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r", "x + y", [x, y]))
        p = projection(r, y, "min")
        assert p.scope_names == ["x"]
        assert p(x=2) == 2  # min over y of x+y = x+0

    def test_projection_max(self):
        r = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r", "x + y", [x, y]))
        p = projection(r, x, "max")
        assert p(y=1) == 3

    def test_dpop_chain(self):
        # join three constraints then eliminate two vars: classic UTIL pass
        r1 = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r1", "1 if x == y else 0", [x, y]))
        r2 = NAryMatrixRelation.from_func_relation(
            constraint_from_str("r2", "1 if y == z else 0", [y, z]))
        j = join(r1, r2)
        p = projection(projection(j, z, "min"), y, "min")
        assert p.scope_names == ["x"]
        assert all(p(x=v) == 0 for v in d3)


class TestHelpers:
    def test_assignment_matrix(self):
        m = assignment_matrix([x, y], default_value=7)
        assert m.shape == (3, 3)
        assert np.all(m == 7)

    def test_generate_assignment_order(self):
        assts = list(generate_assignment_as_dict([x, y]))
        assert len(assts) == 9
        # last variable varies fastest
        assert assts[0] == {"x": 0, "y": 0}
        assert assts[1] == {"x": 0, "y": 1}

    def test_find_optimum(self):
        r = constraint_from_str("r", "x + y", [x, y])
        assert find_optimum(r, "min") == 0
        assert find_optimum(r, "max") == 4

    def test_find_arg_optimal_first_tie(self):
        r = UnaryFunctionRelation("r", x, lambda v: 0)
        vals, cost = find_arg_optimal(x, r, "min")
        assert vals[0] == 0  # first in domain order
        assert len(vals) == 3

    def test_find_optimal(self):
        c = constraint_from_str("c", "1 if x == y else 0", [x, y])
        vals, cost = find_optimal(y, {"x": 1}, [c], "min")
        assert cost == 0
        assert vals == [0, 2]

    def test_optimal_cost_value(self):
        from pydcop_tpu.dcop.objects import VariableWithCostFunc

        v = VariableWithCostFunc("v", d3, lambda val: (val - 1) ** 2)
        val, cost = optimal_cost_value(v, "min")
        assert (val, cost) == (1, 0)

    def test_assignment_cost(self):
        c1 = constraint_from_str("c1", "x + y", [x, y])
        c2 = constraint_from_str("c2", "z", [z])
        assert assignment_cost({"x": 1, "y": 1, "z": 2}, [c1, c2]) == 4

    def test_assignment_cost_hard_violation(self):
        c = constraint_from_str(
            "c", "float('inf') if x == y else 0", [x, y])
        with pytest.raises(ValueError):
            assignment_cost({"x": 1, "y": 1}, [c])

"""Base node/link/graph objects shared by all graph models.

Reference parity: pydcop/computations_graph/objects.py (ComputationNode
:37, Link :136, ComputationGraph :197).
"""

from typing import Dict, Iterable, List, Optional

from pydcop_tpu.utils.simple_repr import SimpleRepr


class Link(SimpleRepr):
    """An undirected link between named computations."""

    def __init__(self, nodes: Iterable[str], link_type: str = "link"):
        self._nodes = tuple(sorted(nodes))
        self._type = link_type

    @property
    def nodes(self):
        return self._nodes

    @property
    def type(self) -> str:
        return self._type

    def has_node(self, name: str) -> bool:
        return name in self._nodes

    def __eq__(self, other):
        return (
            isinstance(other, Link)
            and self._nodes == other._nodes
            and self._type == other._type
        )

    def __hash__(self):
        return hash((self._type, self._nodes))

    def __repr__(self):
        return f"Link({self._type}, {self._nodes})"

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "nodes": list(self._nodes),
            "link_type": self._type,
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["nodes"], r.get("link_type", "link"))


class ComputationNode(SimpleRepr):
    """A named computation in the graph, with its links."""

    def __init__(self, name: str, node_type: str,
                 links: Optional[Iterable[Link]] = None):
        self._name = name
        self._node_type = node_type
        self._links = list(links) if links else []

    @property
    def name(self) -> str:
        return self._name

    @property
    def type(self) -> str:
        return self._node_type

    @property
    def links(self) -> List[Link]:
        return list(self._links)

    @property
    def neighbors(self) -> List[str]:
        """Names of all computations linked to this one (no duplicates,
        insertion order)."""
        seen, out = {self._name}, []
        for link in self._links:
            for n in link.nodes:
                if n not in seen:
                    seen.add(n)
                    out.append(n)
        return out

    def __eq__(self, other):
        return (
            isinstance(other, ComputationNode)
            and self._name == other._name
            and self._node_type == other._node_type
        )

    def __hash__(self):
        return hash((self._name, self._node_type))

    def __repr__(self):
        return f"ComputationNode({self._name!r}, {self._node_type!r})"


class ComputationGraph(SimpleRepr):
    """A set of computation nodes + links, typed by graph model."""

    def __init__(self, graph_type: str,
                 nodes: Optional[Iterable[ComputationNode]] = None):
        self._graph_type = graph_type
        self._nodes: Dict[str, ComputationNode] = {}
        for n in nodes or []:
            self._nodes[n.name] = n

    @property
    def graph_type(self) -> str:
        return self._graph_type

    @property
    def nodes(self) -> List[ComputationNode]:
        return list(self._nodes.values())

    def computation(self, name: str) -> ComputationNode:
        return self._nodes[name]

    def has_computation(self, name: str) -> bool:
        return name in self._nodes

    @property
    def links(self) -> List[Link]:
        seen, out = set(), []
        for n in self._nodes.values():
            for link in n.links:
                if link not in seen:
                    seen.add(link)
                    out.append(link)
        return out

    def density(self) -> float:
        n = len(self._nodes)
        if n < 2:
            return 0.0
        return 2 * len(self.links) / (n * (n - 1))

    def __len__(self):
        return len(self._nodes)

    def __repr__(self):
        return (
            f"ComputationGraph({self._graph_type}, {len(self._nodes)} nodes)"
        )

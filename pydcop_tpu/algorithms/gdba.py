"""GDBA: Generalized Distributed Breakout Algorithm.

Reference parity: pydcop/algorithms/gdba.py (params :181-186: modifier
A/M, violation NZ/NM/MX, increase_mode E/R/C/T; semantics :189-654).
Kernels: pydcop_tpu/ops/gdba.py.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'gdba', max_cycles=30, algo_params={'seed': 1})
    >>> round(res['cost'], 3)
    0.0
"""

from functools import partial
from typing import Optional

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.computations_graph import constraints_hypergraph as chg
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import compile_dcop, validated_aggregation
from pydcop_tpu.engine.runner import DeviceRunResult, run_device_fn
from pydcop_tpu.ops.gdba import run_gdba

GRAPH_TYPE = "constraints_hypergraph"

HEADER_SIZE = 100
UNIT_SIZE = 5

algo_params = [
    # Variable-aggregation strategy for the shared local-search
    # kernels (ops/localsearch.py): "scatter" is the parity
    # default; "ell" replaces every segment_sum/max/min with
    # compile-time dense-gather edge lists (the TPU HBM-regime
    # candidate, benchmarks/exp_aggregation.py).  Single-device;
    # sharded runs always use scatter.
    AlgoParameterDef(
        "aggregation", "str", ["scatter", "ell"], "scatter"
    ),
    AlgoParameterDef("modifier", "str", ["A", "M"], "A"),
    AlgoParameterDef("violation", "str", ["NZ", "NM", "MX"], "NZ"),
    AlgoParameterDef("increase_mode", "str", ["E", "R", "C", "T"], "E"),
    AlgoParameterDef("stop_cycle", "int", None, 0),
    AlgoParameterDef("seed", "int", None, 0),
]


def computation_memory(node) -> float:
    return chg.computation_memory(node)


def communication_load(src, target: str) -> float:
    # ok/improve messages carry a value or an improvement (gdba.py:100).
    return 2 * UNIT_SIZE + HEADER_SIZE


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("gdba", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 1000, mesh=None,
                    n_devices: Optional[int] = None,
                    warmup: bool = False,
                    **_) -> DeviceRunResult:
    from pydcop_tpu.algorithms.mgm import lexic_ranks

    params = algo_def.params
    pad_to = mesh.size if mesh is not None else (n_devices or 1)
    graph, meta = compile_dcop(
        dcop, pad_to=pad_to,
        aggregation=validated_aggregation(params, pad_to))
    fn = partial(
        run_gdba,
        max_cycles=max_cycles,
        modifier_mode=params.get("modifier", "A"),
        violation_mode=params.get("violation", "NZ"),
        increase_mode=params.get("increase_mode", "E"),
        lexic_ranks=lexic_ranks(meta),
        seed=params.get("seed", 0),
    )
    return run_device_fn(graph, meta, fn, mesh=mesh, n_devices=n_devices,
                         warmup=warmup)

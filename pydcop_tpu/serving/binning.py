"""Structure-signature binning for the solve service.

A batched dispatch (engine/batch.run_stacked) requires every instance
in the stack to compile to identical array shapes, and the service
additionally promises that two *different* problem structures never
share a dispatch (same shapes with different scopes would vmap fine
mathematically, but one misrouted meta would decode the wrong
variables — the bin key keeps the invariant structural, not just
dimensional).  The key is the serving-side analogue of the PR-3
structure cache key (engine/compile.CompileCache): variable count,
domain padding, per-bucket shapes and the exact scope-index bytes.

Solver parameters ride in the key too: ``max_cycles``/``damping``/
``stability`` are static arguments of the jitted batched program, so
requests with different parameters can never share one dispatch.
"""

from typing import Any, Dict, Tuple

from pydcop_tpu.engine.compile import CompiledFactorGraph

# Solver parameters that are static in the batched program — the
# params half of the bin key, in canonical order.  ``prune`` rides in
# the key because the pruned and dense batched programs are different
# executables (same results — pruning never changes values).
PARAM_KEYS = ("max_cycles", "damping", "damping_nodes", "stability",
              "noise", "prune")

DEFAULT_PARAMS: Dict[str, Any] = {
    "max_cycles": 200,
    "damping": 0.5,
    "damping_nodes": "both",
    "stability": 0.1,
    "noise": 0.01,
    # 0 = dense, 1 = branch-and-bound pruning, "auto" = replay the
    # portfolio racer's cached decision for this structure (resolved
    # to 0/1 at submit, AFTER the graph compiles — never measured on
    # the serving path).
    "prune": 0,
}


DAMPING_NODES = ("vars", "factors", "both", "none")


def normalize_params(overrides: Dict[str, Any] = None) -> Dict[str, Any]:
    """Fill a request's solver-parameter dict from the service
    defaults, rejecting unknown keys (a typo'd parameter silently
    falling back to a default would be a debugging trap) and
    canonicalizing every value's type — the values land in a hashable
    bin key AND in the jitted program's static arguments, so an
    unhashable or wrong-typed value must fail the submit (a 400), not
    the scheduler thread."""
    params = dict(DEFAULT_PARAMS)
    for key, value in (overrides or {}).items():
        if key not in DEFAULT_PARAMS:
            raise ValueError(
                f"unknown solver parameter {key!r}; valid: "
                f"{', '.join(PARAM_KEYS)}"
            )
        params[key] = value
    try:
        params["max_cycles"] = int(params["max_cycles"])
        for key in ("damping", "stability", "noise"):
            params[key] = float(params[key])
    except (TypeError, ValueError) as exc:
        raise ValueError(f"bad solver parameter value: {exc}")
    if params["prune"] != "auto":
        try:
            params["prune"] = int(params["prune"])
        except (TypeError, ValueError):
            params["prune"] = -1  # falls through to the check below
    if params["prune"] not in (0, 1, "auto"):
        raise ValueError(
            f"prune must be 0, 1 or 'auto', got "
            f"{(overrides or {}).get('prune')!r}")
    if params["damping_nodes"] not in DAMPING_NODES:
        raise ValueError(
            f"damping_nodes must be one of {DAMPING_NODES}, got "
            f"{params['damping_nodes']!r}")
    return params


def structure_signature(graph: CompiledFactorGraph) -> Tuple:
    """Hashable structural identity of a compiled graph.

    Shapes alone define *stackability*; the scope-index bytes make the
    signature injective over topologies, which is what "two structures
    never share a dispatch" needs.  Cost tables are deliberately NOT
    in the signature — same-structure requests with different costs
    are exactly the traffic that should coalesce.
    """
    return (
        graph.var_costs.shape,
        tuple(
            (b.costs.shape, b.var_ids.tobytes()) for b in graph.buckets
        ),
        # Aggregation layout arrays change the compiled program shape.
        tuple(
            None if a is None else a.shape
            for a in (graph.agg_perm, graph.agg_sorted_seg,
                      graph.agg_starts, graph.agg_ends, graph.agg_ell)
        ),
    )


def bin_key(graph: CompiledFactorGraph,
            params: Dict[str, Any]) -> Tuple:
    """The scheduler's bin key: structure signature + solver params."""
    return (
        structure_signature(graph),
        tuple((k, params[k]) for k in PARAM_KEYS),
    )


def bin_label(key: Tuple) -> str:
    """Short low-cardinality label for a bin key (metrics/trace): the
    variable-count/domain part of the shape plus a process-stable
    digest of the rest — full keys embed scope bytes and would
    explode label cardinality, and the built-in ``hash`` is
    per-process randomized (labels must survive restarts so merged
    traces from two serving processes correlate by bin)."""
    import hashlib

    (var_shape, _buckets, _agg), _params = key
    digest = hashlib.sha1(repr(key).encode()).hexdigest()[:6]
    return f"v{var_shape[0] - 1}d{var_shape[1]}h{digest}"

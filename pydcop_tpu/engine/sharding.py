"""Mesh construction and sharding for the device engine.

Two sharding stories live here:

**Replicated-variable sharding** (:func:`shard_graph`, the original
scaling-book recipe): factor buckets row-shard over a one-axis mesh,
variable tables replicate, and the per-superstep segment-sum into the
replicated ``[V+1, D]`` totals is the one collective XLA inserts (an
all-reduce over ICI).  Simple and algorithm-agnostic — every device
algorithm rides it via ``n_devices`` — but the all-reduce moves
O(V·D) per superstep no matter how local the graph is.

**Partitioned sharding** (:func:`build_partitioned_graph` +
:class:`ShardOps`, the ``shards=`` path): a host-side min-edge-cut
partition (engine/partition.py) assigns variables AND factors to
shards; each shard owns a local slice of the variable tables and the
messages of its own factors, interior message updates are purely
local, and only HALO variables — endpoints of cut edges — are
exchanged per superstep through a compacted ``[B, D]`` boundary
buffer (``jax.lax.psum`` inside ``shard_map``).  Communication volume
becomes O(cut·D) instead of O(V·D).  The superstep further splits
into interior and boundary sub-updates: the boundary partial sums of
the messages just sent are psum'd at the TAIL of superstep *t* into a
double-buffered halo slot that superstep *t+1* consumes at its head —
the halo exchange of one cycle overlaps the interior factor→variable
work XLA schedules around it, without changing the BSP semantics
(the variable side always reads the previous cycle's factor
messages, so the "stale-looking" buffer is exactly the right one).

This replaces the reference's distribution-of-computations-over-agents
as the *intra-pod* scaling mechanism (reference: pydcop/distribution/);
the distribution algorithms remain for agent-mode and for balancing
which factors land on which shard.
"""

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pydcop_tpu.engine.compile import (
    BIG,
    CompiledFactorGraph,
    FactorBucket,
)
from pydcop_tpu.engine.partition import Partition, real_factor_rows
from pydcop_tpu.ops import maxsum as maxsum_ops

SHARD_AXIS = "shard"


def make_mesh(n_devices: Optional[int] = None,
              devices=None) -> Mesh:
    """A 1-D mesh over (the first n of) the available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices but only "
                f"{len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def shard_graph(graph: CompiledFactorGraph,
                mesh: Mesh) -> CompiledFactorGraph:
    """Place the compiled graph on the mesh: buckets sharded on the
    factor axis, variable tables replicated.

    Bucket rows not divisible by the mesh size are auto-padded with
    sentinel rows (zero cost, var_ids pointing at the sentinel
    variable — identical to compile-time ``pad_to`` padding), so
    callers no longer have to know the mesh size at compile time.
    """
    replicated = NamedSharding(mesh, P())
    row_sharded = NamedSharding(mesh, P(SHARD_AXIS))
    sentinel = graph.var_costs.shape[0] - 1
    buckets = []
    for b in graph.buckets:
        costs = np.asarray(b.costs)
        var_ids = np.asarray(b.var_ids)
        pad = (-costs.shape[0]) % mesh.size
        if pad:
            costs = np.concatenate(
                [costs,
                 np.zeros((pad,) + costs.shape[1:], costs.dtype)],
                axis=0)
            var_ids = np.concatenate(
                [var_ids,
                 np.full((pad, var_ids.shape[1]), sentinel,
                         var_ids.dtype)],
                axis=0)
        buckets.append(FactorBucket(
            costs=jax.device_put(costs, row_sharded),
            var_ids=jax.device_put(var_ids, row_sharded),
        ))
    return CompiledFactorGraph(
        var_costs=jax.device_put(graph.var_costs, replicated),
        var_valid=jax.device_put(graph.var_valid, replicated),
        buckets=tuple(buckets),
    )


# --------------------------------------------------------------------- #
# Partitioned sharding: per-shard variable slices + halo exchange.


class ShardBucket(NamedTuple):
    """One arity bucket, stacked per shard: leading axis S, var_ids in
    the shard-LOCAL variable index space (see ShardedGraph)."""

    costs: Any     # [S, F, Dmax]*arity
    var_ids: Any   # [S, F, arity] int32, local L-space


class ShardedGraph(NamedTuple):
    """Partitioned device layout.  Every array has a leading shard
    axis S and is placed ``P('shard')`` — inside ``shard_map`` each
    shard sees its own block.

    Local variable index space per shard (size ``L``): slots
    ``[0, V_loc)`` hold OWNED variables (padded across shards to the
    max owned count), ``[V_loc, V_loc + H)`` hold HALO variables
    (owned elsewhere, referenced by local factors; cost rows are
    copies of the owner's rows so beliefs compute identically), and
    slot ``L-1`` is the sentinel absorbing padding edges.

    The boundary buffer covers the B variables that are halo for at
    least one shard; ``bnd_*``/``halo_bnd``/``bnd_edge_*`` are the
    index plumbing for the O(B·D) halo exchange (see ShardOps).
    """

    var_costs: Any     # [S, L, D] f32
    var_valid: Any     # [S, L, D] bool
    buckets: Tuple[ShardBucket, ...]
    local_global: Any  # [S, L-1] int32: global id per local slot (V=pad)
    bnd_local: Any     # [S, B] int32: local slot of boundary var b (L-1 if absent)
    bnd_present: Any   # [S, B] bool: shard holds a slot for b
    bnd_owner: Any     # [S, B] bool: shard owns b
    halo_bnd: Any      # [S, H] int32: boundary index of halo slot h (B=pad)
    bnd_edge_idx: Any  # [S, Eb] int32: flat f2v edge index of boundary edges
    bnd_edge_seg: Any  # [S, Eb] int32: boundary index of that edge (B=pad)

    @property
    def n_shards(self) -> int:
        return self.var_costs.shape[0]

    @property
    def dmax(self) -> int:
        return self.var_costs.shape[-1]

    @property
    def n_boundary(self) -> int:
        return self.bnd_local.shape[-1]

    @property
    def v_loc(self) -> int:
        return self.local_global.shape[-1] - self.halo_bnd.shape[-1]


class ShardedMaxSumState(NamedTuple):
    """MaxSum state for the partitioned engine.  Messages are stacked
    per shard ([S, F, arity, D], sharded); ``halo`` is the
    double-buffered boundary-sum slot — the psum'd totals of the
    CURRENT ``f2v`` messages, computed at the tail of the superstep
    that sent them and consumed at the head of the next one.
    ``stable``/``cycle`` are replicated scalars (``stable`` is the
    psum-combined global verdict, ``cycle`` advances identically on
    every shard)."""

    v2f: Tuple[Any, ...]
    f2v: Tuple[Any, ...]
    v2f_count: Tuple[Any, ...]
    f2v_count: Tuple[Any, ...]
    halo: Any      # [B, D] f32, replicated
    stable: Any    # scalar bool
    cycle: Any     # scalar int32


def build_partitioned_graph(graph: CompiledFactorGraph,
                            part: Partition, mesh: Mesh
                            ) -> Tuple[ShardedGraph, Dict[str, Any]]:
    """Materialize the per-shard layout for a partition: local
    variable tables (owned + halo + sentinel), locally-reindexed
    factor buckets, and the boundary-exchange index arrays.  Returns
    the placed ShardedGraph plus the metrics dict (partition stats +
    communication accounting)."""
    n_shards = mesh.size
    if part.n_shards != n_shards:
        raise ValueError(
            f"partition has {part.n_shards} shards but mesh has "
            f"{n_shards} devices")
    n_vars = graph.n_vars
    d = graph.dmax
    var_shard = part.var_shard
    var_costs = np.asarray(graph.var_costs)
    var_valid = np.asarray(graph.var_valid)

    owned = [np.nonzero(var_shard == s)[0] for s in range(n_shards)]
    # Per-bucket real rows + their shard assignment (padding rows of
    # the input graph are dropped; per-shard padding is rebuilt).
    bucket_rows = []
    for b, fs in zip(graph.buckets, part.factor_shard):
        ids = np.asarray(b.var_ids)
        rows = real_factor_rows(ids, n_vars)
        if rows.shape[0] != fs.shape[0]:
            raise ValueError(
                "partition factor assignment does not match the "
                f"graph ({rows.shape[0]} real factors vs "
                f"{fs.shape[0]} assigned)")
        bucket_rows.append((ids, np.asarray(b.costs), rows, fs))

    halo = []
    for s in range(n_shards):
        touched: list = []
        for ids, _, rows, fs in bucket_rows:
            sel = rows[fs == s]
            if sel.size:
                touched.append(np.unique(ids[sel]))
        all_touched = (np.unique(np.concatenate(touched))
                       if touched else np.zeros((0,), np.int64))
        halo.append(np.setdiff1d(all_touched, owned[s]))

    v_loc = max((len(o) for o in owned), default=0)
    v_loc = max(v_loc, 1)
    n_halo = max((len(h) for h in halo), default=0)
    L = v_loc + n_halo + 1

    bnd_list = (np.unique(np.concatenate(halo))
                if any(h.size for h in halo)
                else np.zeros((0,), np.int64))
    n_bnd = len(bnd_list)
    bnd_of = np.full(n_vars + 1, n_bnd, np.int64)
    bnd_of[bnd_list] = np.arange(n_bnd)

    s_var_costs = np.full((n_shards, L, d), BIG, var_costs.dtype)
    s_var_valid = np.zeros((n_shards, L, d), bool)
    s_local_global = np.full((n_shards, L - 1), n_vars, np.int32)
    s_bnd_local = np.full((n_shards, max(n_bnd, 0)), L - 1, np.int32)
    s_bnd_present = np.zeros((n_shards, n_bnd), bool)
    s_bnd_owner = np.zeros((n_shards, n_bnd), bool)
    s_halo_bnd = np.full((n_shards, n_halo), n_bnd, np.int32)

    local_of = np.full((n_shards, n_vars + 1), L - 1, np.int64)
    for s in range(n_shards):
        o, h = owned[s], halo[s]
        local_of[s, o] = np.arange(len(o))
        local_of[s, h] = v_loc + np.arange(len(h))
        rows = np.concatenate([o, h]).astype(np.int64)
        slots = local_of[s, rows]
        s_var_costs[s, slots] = var_costs[rows]
        s_var_valid[s, slots] = var_valid[rows]
        s_local_global[s, slots] = rows
        if n_bnd:
            s_bnd_local[s] = local_of[s, bnd_list]
            s_bnd_present[s] = s_bnd_local[s] != (L - 1)
            s_bnd_owner[s] = var_shard[bnd_list] == s
        if len(h):
            s_halo_bnd[s, :len(h)] = bnd_of[h]

    # Per-bucket local layouts, padded to the max per-shard factor
    # count so the stacked arrays are rectangular.
    buckets = []
    bucket_pad_counts = []
    flat_offsets = []
    offset = 0
    for ids, costs, rows, fs in bucket_rows:
        arity = ids.shape[1]
        counts = [int((fs == s).sum()) for s in range(n_shards)]
        f_max = max(counts + [0])
        s_costs = np.zeros((n_shards, f_max) + costs.shape[1:],
                           costs.dtype)
        s_ids = np.full((n_shards, f_max, arity), L - 1, np.int32)
        for s in range(n_shards):
            sel = rows[fs == s]
            k = sel.shape[0]
            if k:
                s_costs[s, :k] = costs[sel]
                s_ids[s, :k] = local_of[s][ids[sel]]
        buckets.append(ShardBucket(costs=s_costs, var_ids=s_ids))
        bucket_pad_counts.append(f_max)
        flat_offsets.append(offset)
        offset += f_max * arity
    total_edges = offset

    # Boundary-incident edges per shard, in the flat f2v order the
    # kernels use (bucket order, row-major [F, arity]).  These drive
    # the O(cut) boundary sub-update: the halo partial sums aggregate
    # ONLY these edges, never the interior ones.
    is_bnd_slot = np.zeros((n_shards, L), bool)
    for s in range(n_shards):
        if n_bnd:
            pres = s_bnd_present[s]
            is_bnd_slot[s, s_bnd_local[s][pres]] = True
    edge_idx = [[] for _ in range(n_shards)]
    edge_seg = [[] for _ in range(n_shards)]
    slot_bnd = np.full((n_shards, L), n_bnd, np.int64)
    for s in range(n_shards):
        if n_bnd:
            pres = s_bnd_present[s]
            slot_bnd[s, s_bnd_local[s][pres]] = np.nonzero(pres)[0]
    for bi, bucket in enumerate(buckets):
        arity = bucket.var_ids.shape[2]
        for s in range(n_shards):
            lids = bucket.var_ids[s].reshape(-1)
            sel = np.nonzero(is_bnd_slot[s][lids])[0]
            edge_idx[s].append(flat_offsets[bi] + sel)
            edge_seg[s].append(slot_bnd[s][lids[sel]])
    e_max = 0
    for s in range(n_shards):
        edge_idx[s] = (np.concatenate(edge_idx[s])
                       if edge_idx[s] else np.zeros((0,), np.int64))
        edge_seg[s] = (np.concatenate(edge_seg[s])
                       if edge_seg[s] else np.zeros((0,), np.int64))
        e_max = max(e_max, edge_idx[s].shape[0])
    s_edge_idx = np.zeros((n_shards, e_max), np.int32)
    s_edge_seg = np.full((n_shards, e_max), n_bnd, np.int32)
    for s in range(n_shards):
        k = edge_idx[s].shape[0]
        s_edge_idx[s, :k] = edge_idx[s]
        s_edge_seg[s, :k] = edge_seg[s]

    sharded = ShardedGraph(
        var_costs=s_var_costs,
        var_valid=s_var_valid,
        buckets=tuple(buckets),
        local_global=s_local_global,
        bnd_local=s_bnd_local,
        bnd_present=s_bnd_present,
        bnd_owner=s_bnd_owner,
        halo_bnd=s_halo_bnd,
        bnd_edge_idx=s_edge_idx,
        bnd_edge_seg=s_edge_seg,
    )
    row_sharded = NamedSharding(mesh, P(SHARD_AXIS))
    sharded = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, row_sharded), sharded)
    # Communication accounting: what one superstep moves between
    # shards on each path.  The partitioned exchange is the [B, D]
    # halo psum (+ one scalar convergence flag); the replicated
    # baseline all-reduces the dense [V+1, D] totals.  The shard-smoke
    # gate asserts partitioned < replicated.
    metrics = {
        **part.stats,
        "halo_exchange_elems_per_superstep": n_bnd * d,
        "replicated_allreduce_elems_per_superstep": (n_vars + 1) * d,
        "halo_exchange_bytes_per_superstep": n_bnd * d * 4,
        "replicated_allreduce_bytes_per_superstep":
            (n_vars + 1) * d * 4,
        "boundary_edges_per_shard_max": int(e_max),
        "local_factor_rows_per_shard": list(bucket_pad_counts),
        "total_flat_edges": int(total_edges),
    }
    return sharded, metrics


# ---------------------------- device kernels ------------------------- #


def _unblock_graph(g: ShardedGraph):
    """Strip the leading per-shard block axis: inside shard_map a
    shard's slice of the graph is just a CompiledFactorGraph over the
    local L-space, plus the boundary-index aux arrays."""
    lgraph = CompiledFactorGraph(
        var_costs=g.var_costs[0],
        var_valid=g.var_valid[0],
        buckets=tuple(
            FactorBucket(b.costs[0], b.var_ids[0]) for b in g.buckets
        ),
    )
    aux = g._replace(
        var_costs=g.var_costs[0], var_valid=g.var_valid[0],
        buckets=(), local_global=g.local_global[0],
        bnd_local=g.bnd_local[0], bnd_present=g.bnd_present[0],
        bnd_owner=g.bnd_owner[0], halo_bnd=g.halo_bnd[0],
        bnd_edge_idx=g.bnd_edge_idx[0], bnd_edge_seg=g.bnd_edge_seg[0],
    )
    return lgraph, aux


def _unblock_state(st: ShardedMaxSumState) -> ShardedMaxSumState:
    sq = lambda t: tuple(m[0] for m in t)  # noqa: E731
    return st._replace(v2f=sq(st.v2f), f2v=sq(st.f2v),
                       v2f_count=sq(st.v2f_count),
                       f2v_count=sq(st.f2v_count))


def _reblock_state(st: ShardedMaxSumState) -> ShardedMaxSumState:
    ex = lambda t: tuple(m[None] for m in t)  # noqa: E731
    return st._replace(v2f=ex(st.v2f), f2v=ex(st.f2v),
                       v2f_count=ex(st.v2f_count),
                       f2v_count=ex(st.f2v_count))


def _local_sums(lgraph: CompiledFactorGraph, f2v) -> jnp.ndarray:
    """Shard-local variable aggregation (the interior sub-update):
    the single-device scatter path of ops.maxsum.aggregate_beliefs on
    the local block (local graphs never carry agg_* arrays, so the
    scatter branch is guaranteed; the unused beliefs output is
    dead-code-eliminated by XLA).  Interior variables get their FULL
    sums here (all their factors are local by construction); boundary
    slots get this shard's partial, overwritten by the halo buffer in
    _combine_halo."""
    _, sums = maxsum_ops.aggregate_beliefs(lgraph, f2v)
    return sums


def _combine_halo(sums: jnp.ndarray, halo: jnp.ndarray,
                  aux) -> jnp.ndarray:
    """Overwrite boundary rows of the local sums with the exchanged
    global totals.  Absent boundary vars map to the sentinel slot and
    rewrite its (garbage) row with itself — a no-op."""
    if halo.shape[0] == 0:
        return sums
    rows = jnp.where(aux.bnd_present[:, None], halo,
                     sums[aux.bnd_local])
    return sums.at[aux.bnd_local].set(rows)


def _exchange_halo(f2v, aux, n_boundary: int) -> jnp.ndarray:
    """The boundary sub-update + halo exchange: partial sums over ONLY
    the boundary-incident edges of the just-sent factor messages,
    all-reduced across the mesh into the [B, D] double buffer.  This
    is the single O(cut·D) collective of the partitioned superstep;
    issued at the superstep tail so XLA can overlap it with the next
    superstep's interior factor work.  Callers skip the call entirely
    when ``n_boundary`` is 0 (an edge-free or perfectly-partitioned
    graph exchanges nothing)."""
    d = f2v[0].shape[-1]
    flats = [m.reshape(-1, d) for m in f2v]
    flat = flats[0] if len(flats) == 1 else jnp.concatenate(flats, 0)
    contrib = flat[aux.bnd_edge_idx]            # [Eb, D]
    partials = jax.ops.segment_sum(
        contrib, aux.bnd_edge_seg, num_segments=n_boundary + 1,
    )[:n_boundary]
    return jax.lax.psum(partials, SHARD_AXIS)


def _global_all(flag: jnp.ndarray) -> jnp.ndarray:
    """AND a per-shard bool across the mesh (a 4-byte collective)."""
    return jax.lax.psum(flag.astype(jnp.int32), SHARD_AXIS) \
        == jax.lax.psum(1, SHARD_AXIS)


def _superstep_local(lgraph, aux, st: ShardedMaxSumState, *,
                     damping: float, damp_vars: bool,
                     damp_factors: bool, stability: float,
                     n_boundary: int,
                     prune=None) -> ShardedMaxSumState:
    """One partitioned MaxSum superstep on one shard's block — the
    exact semantics of ops.maxsum.superstep (Jacobi BSP, damping,
    SAME_COUNT send-suppression), with the variable aggregation split
    into the interior sub-update (_local_sums) plus the halo buffer
    consumed from the PREVIOUS superstep's tail exchange."""
    first = st.cycle == 0
    valids = tuple(
        lgraph.var_valid[b.var_ids] for b in lgraph.buckets
    )

    f2v_cand = maxsum_ops.factor_to_var(lgraph, st.v2f, prune=prune)
    if damp_factors and damping > 0:
        f2v_cand = maxsum_ops._damp(f2v_cand, st.f2v, damping, first)

    # Variable side reads the PREVIOUS cycle's factor messages; the
    # halo slot holds exactly their boundary totals (exchanged at the
    # tail of the previous superstep), so consuming it here is
    # semantics-preserving double buffering, not staleness.
    sums = _combine_halo(_local_sums(lgraph, st.f2v), st.halo, aux)
    beliefs = lgraph.var_costs + sums
    v2f_cand = maxsum_ops.var_to_factor(lgraph, st.f2v, beliefs, sums)
    if damp_vars and damping > 0:
        v2f_cand = maxsum_ops._damp(v2f_cand, st.v2f, damping, first)

    f2v_new, f2v_count = [], []
    v2f_new, v2f_count = [], []
    all_match = jnp.asarray(True)
    for i, valid in enumerate(valids):
        sent, cnt, match = maxsum_ops._send_or_suppress(
            f2v_cand[i], st.f2v[i], st.f2v_count[i],
            stability, valid, first)
        f2v_new.append(sent)
        f2v_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, -1))
        sent, cnt, match = maxsum_ops._send_or_suppress(
            v2f_cand[i], st.v2f[i], st.v2f_count[i],
            stability, valid, first)
        v2f_new.append(sent)
        v2f_count.append(cnt)
        all_match = all_match & jnp.all(match | ~jnp.any(valid, -1))

    halo_new = (_exchange_halo(tuple(f2v_new), aux, n_boundary)
                if n_boundary else st.halo)
    stable = _global_all(all_match) & ~first
    return ShardedMaxSumState(
        v2f=tuple(v2f_new),
        f2v=tuple(f2v_new),
        v2f_count=tuple(v2f_count),
        f2v_count=tuple(f2v_count),
        halo=halo_new,
        stable=stable,
        cycle=st.cycle + 1,
    )


def _select_local(lgraph, aux, st, v_loc: int) -> jnp.ndarray:
    """Per-shard value selection over OWNED rows ([V_loc] int32)."""
    sums = _combine_halo(_local_sums(lgraph, st.f2v), st.halo, aux)
    beliefs = lgraph.var_costs + sums
    masked = jnp.where(lgraph.var_valid, beliefs, jnp.inf)
    return jnp.argmin(masked[:v_loc], axis=1).astype(jnp.int32)


def _exchange_values(values_owned, aux, v_loc: int, n_halo: int,
                     n_boundary: int) -> jnp.ndarray:
    """Owner-scatter + psum of the selected values of boundary vars,
    gathered back into this shard's halo slots ([H] int32) — the
    value-plane halo exchange cost traces need."""
    if n_boundary == 0:
        return jnp.zeros((n_halo,), jnp.int32)
    vals_pad = jnp.concatenate(
        [values_owned,
         jnp.zeros((n_halo + 1,), jnp.int32)])
    owner_vals = jnp.where(
        aux.bnd_owner, vals_pad[aux.bnd_local], 0)
    bnd_vals = jax.lax.psum(owner_vals, SHARD_AXIS)      # [B]
    bnd_ext = jnp.concatenate(
        [bnd_vals, jnp.zeros((1,), jnp.int32)])
    return bnd_ext[aux.halo_bnd]


class ShardOps:
    """ops.maxsum-compatible kernel namespace for a partitioned graph
    — MaxSumEngine's ``_ops`` seam lets the whole segmented/
    checkpointed/recovery runner machinery drive these unchanged.
    Holds the mesh and the global variable count (the only statics a
    ShardedGraph's array shapes cannot express)."""

    def __init__(self, mesh: Mesh, n_vars: int):
        self.mesh = mesh
        self.n_vars = n_vars

    # -- spec plumbing -------------------------------------------------- #

    def _graph_specs(self, graph: ShardedGraph):
        shard = P(SHARD_AXIS)
        return graph._replace(
            var_costs=shard, var_valid=shard,
            buckets=tuple(ShardBucket(shard, shard)
                          for _ in graph.buckets),
            local_global=shard, bnd_local=shard, bnd_present=shard,
            bnd_owner=shard, halo_bnd=shard,
            bnd_edge_idx=shard, bnd_edge_seg=shard,
        )

    def _state_specs(self, graph: ShardedGraph):
        shard = P(SHARD_AXIS)
        nb = len(graph.buckets)
        return ShardedMaxSumState(
            v2f=(shard,) * nb, f2v=(shard,) * nb,
            v2f_count=(shard,) * nb, f2v_count=(shard,) * nb,
            halo=P(), stable=P(), cycle=P(),
        )

    # -- state construction --------------------------------------------- #

    def _zeros_state(self, graph: ShardedGraph) -> ShardedMaxSumState:
        d = graph.dmax
        dtype = graph.var_costs.dtype
        msgs = tuple(
            jnp.zeros(b.var_ids.shape + (d,), dtype=dtype)
            for b in graph.buckets
        )
        counts = tuple(
            jnp.zeros(b.var_ids.shape, dtype=jnp.int8)
            for b in graph.buckets
        )
        # De-aliased per field (donation rejects duplicated buffers),
        # mirroring ops.maxsum.init_state.
        def zeros():
            return tuple(jnp.zeros_like(m) for m in msgs)

        def czeros():
            return tuple(jnp.zeros_like(c) for c in counts)

        return ShardedMaxSumState(
            v2f=zeros(), f2v=zeros(),
            v2f_count=czeros(), f2v_count=czeros(),
            halo=jnp.zeros((graph.n_boundary, d), dtype=dtype),
            stable=jnp.asarray(False),
            cycle=jnp.asarray(0, dtype=jnp.int32),
        )

    def init_state(self, graph: ShardedGraph) -> ShardedMaxSumState:
        """Placed initial state — also the checkpoint template
        (resilience/checkpoint.py restores snapshots into this exact
        pytree: shapes, dtypes AND shardings)."""
        state = self._zeros_state(graph)
        shard = NamedSharding(self.mesh, P(SHARD_AXIS))
        rep = NamedSharding(self.mesh, P())
        put = lambda t: tuple(  # noqa: E731
            jax.device_put(m, shard) for m in t)
        return state._replace(
            v2f=put(state.v2f), f2v=put(state.f2v),
            v2f_count=put(state.v2f_count),
            f2v_count=put(state.f2v_count),
            halo=jax.device_put(state.halo, rep),
            stable=jax.device_put(state.stable, rep),
            cycle=jax.device_put(state.cycle, rep),
        )

    # -- solve entry points (maxsum_ops signatures) ---------------------- #

    def run_maxsum_from(self, graph: ShardedGraph,
                        state: ShardedMaxSumState,
                        extra_cycles: int, *,
                        damping: float = 0.5, damp_vars: bool = True,
                        damp_factors: bool = True,
                        stability: float = 0.1,
                        stop_on_convergence: bool = True,
                        prune: bool = False):
        """Up to ``extra_cycles`` more partitioned supersteps from an
        existing state; returns ``(state, values)`` with ``values``
        reassembled to the GLOBAL [V] order (identical interface to
        ops.maxsum.run_maxsum_from, so the segmented runner, the
        checkpoint format and the recovery ladder work unchanged).

        ``prune=True`` applies branch-and-bound pruning to each
        shard's local factor reductions with the same dense/compacted
        phase alternation as the edge-major kernel; the phase
        predicate is the GLOBAL AND of the per-shard fit tests (one
        4-byte collective per loop-condition evaluation), so every
        shard always runs the same kernel and the collectives inside
        the superstep stay aligned."""
        n_bnd = graph.n_boundary
        v_loc = graph.v_loc

        def local_run(g, st):
            lgraph, aux = _unblock_graph(g)
            st = _unblock_state(st)
            step = partial(
                _superstep_local, lgraph, aux,
                damping=damping, damp_vars=damp_vars,
                damp_factors=damp_factors, stability=stability,
                n_boundary=n_bnd,
            )
            limit = st.cycle + extra_cycles
            if stop_on_convergence:
                done = lambda s: (s.cycle >= limit) | s.stable  # noqa: E731
            else:
                done = lambda s: s.cycle >= limit  # noqa: E731
            pt = maxsum_ops.prune_tables(lgraph) if prune else None
            if pt is not None and all(t is None for t in pt):
                pt = None
            if pt is None:
                st = jax.lax.while_loop(
                    lambda s: ~done(s), lambda s: step(st=s), st)
            else:
                step_fast = partial(
                    _superstep_local, lgraph, aux,
                    damping=damping, damp_vars=damp_vars,
                    damp_factors=damp_factors, stability=stability,
                    n_boundary=n_bnd, prune=pt,
                )

                def fits(s):
                    return _global_all(
                        maxsum_ops.prune_fits(s.v2f, pt))

                def phases(s):
                    s = jax.lax.while_loop(
                        lambda s: ~done(s) & ~fits(s),
                        lambda s: step(st=s), s)
                    s = jax.lax.while_loop(
                        lambda s: ~done(s) & fits(s),
                        lambda s: step_fast(st=s), s)
                    return s

                st = jax.lax.while_loop(
                    lambda s: ~done(s), phases, st)
            values = _select_local(lgraph, aux, st, v_loc)
            return _reblock_state(st), values[None]

        mapped = shard_map(
            local_run, mesh=self.mesh,
            in_specs=(self._graph_specs(graph),
                      self._state_specs(graph)),
            out_specs=(self._state_specs(graph), P(SHARD_AXIS)),
            check_rep=False,
        )
        state, values_sh = mapped(graph, state)
        return state, self._assemble_values(graph, values_sh)

    def run_maxsum(self, graph: ShardedGraph, max_cycles: int, *,
                   damping: float = 0.5, damp_vars: bool = True,
                   damp_factors: bool = True, stability: float = 0.1,
                   stop_on_convergence: bool = True,
                   prune: bool = False):
        return self.run_maxsum_from(
            graph, self._zeros_state(graph), max_cycles,
            damping=damping, damp_vars=damp_vars,
            damp_factors=damp_factors, stability=stability,
            stop_on_convergence=stop_on_convergence, prune=prune,
        )

    def run_maxsum_trace(self, graph: ShardedGraph, max_cycles: int, *,
                         damping: float = 0.5, damp_vars: bool = True,
                         damp_factors: bool = True,
                         stability: float = 0.1,
                         var_base_costs=None,
                         stop_on_convergence: bool = True,
                         prune: bool = False):
        """Partitioned run recording the global assignment cost after
        every cycle: per-shard constraint cost over local factors +
        owned-variable base costs, psum'd — each factor and each
        variable is owned by exactly one shard, so the psum is a
        partition of the global sum (no double counting).  Halo
        variables' selected values ride a [B]-int exchange.

        Early exit (``stop_on_convergence``) mirrors the edge-major
        trace: a while_loop writes each cycle's cost into a carried
        buffer and the tail holds the final value; every shard leaves
        the loop on the same (globally-reduced) verdict.  ``prune`` is
        accepted for ops-interface parity but runs dense: pruning
        never changes values, and a trace is a value record."""
        n_bnd = graph.n_boundary
        v_loc = graph.v_loc
        n_halo = graph.local_global.shape[-1] - v_loc
        d = graph.dmax
        if var_base_costs is not None:
            base_ext = jnp.concatenate(
                [jnp.asarray(var_base_costs),
                 jnp.zeros((1, d), jnp.asarray(var_base_costs).dtype)],
                axis=0)
            base_local = base_ext[graph.local_global[:, :v_loc]]
        else:
            base_local = jnp.zeros(
                (graph.n_shards, v_loc, d), graph.var_costs.dtype)

        def local_run(g, base):
            lgraph, aux = _unblock_graph(g)
            base = base[0]
            step_fn = partial(
                _superstep_local, lgraph, aux,
                damping=damping, damp_vars=damp_vars,
                damp_factors=damp_factors, stability=stability,
                n_boundary=n_bnd,
            )

            def cost_of(st):
                values = _select_local(lgraph, aux, st, v_loc)
                halo_vals = _exchange_values(
                    values, aux, v_loc, n_halo, n_bnd)
                vals_full = jnp.concatenate([values, halo_vals])
                cost = maxsum_ops.assignment_constraint_cost(
                    lgraph, vals_full)
                if var_base_costs is not None:
                    cost = cost + jnp.sum(jnp.take_along_axis(
                        base, values[:, None], axis=1))
                return jax.lax.psum(cost, SHARD_AXIS), values

            def step(carry):
                st, costs, last = carry
                st = step_fn(st=st)
                cost, _ = cost_of(st)
                costs = jax.lax.dynamic_update_slice(
                    costs, cost[None], (st.cycle - 1,))
                return st, costs, cost

            def done(carry):
                st = carry[0]
                out = st.cycle >= max_cycles
                if stop_on_convergence:
                    # st.stable is already the global AND
                    # (_global_all inside the superstep), so every
                    # shard exits together.
                    out = out | st.stable
                return out

            zero = jnp.asarray(0.0, lgraph.var_costs.dtype)
            st, costs, last = jax.lax.while_loop(
                lambda c: ~done(c), step,
                (self._zeros_state_local(lgraph, n_bnd),
                 jnp.zeros((max_cycles,), lgraph.var_costs.dtype),
                 zero))
            costs = jnp.where(
                jnp.arange(max_cycles) >= st.cycle, last, costs)
            _, values = cost_of(st)
            return _reblock_state(st), values[None], costs

        mapped = shard_map(
            local_run, mesh=self.mesh,
            in_specs=(self._graph_specs(graph), P(SHARD_AXIS)),
            out_specs=(self._state_specs(graph), P(SHARD_AXIS), P()),
            check_rep=False,
        )
        state, values_sh, costs = mapped(graph, base_local)
        return state, self._assemble_values(graph, values_sh), costs

    def _zeros_state_local(self, lgraph, n_bnd: int
                           ) -> ShardedMaxSumState:
        d = lgraph.var_costs.shape[1]
        dtype = lgraph.var_costs.dtype

        def zeros():
            return tuple(
                jnp.zeros(b.var_ids.shape + (d,), dtype=dtype)
                for b in lgraph.buckets)

        def counts():
            return tuple(
                jnp.zeros(b.var_ids.shape, dtype=jnp.int8)
                for b in lgraph.buckets)

        return ShardedMaxSumState(
            v2f=zeros(), f2v=zeros(),
            v2f_count=counts(), f2v_count=counts(),
            halo=jnp.zeros((n_bnd, d), dtype=dtype),
            stable=jnp.asarray(False),
            cycle=jnp.asarray(0, dtype=jnp.int32),
        )

    def recompute_halo(self, graph: ShardedGraph, f2v) -> jnp.ndarray:
        """The ``[B, D]`` boundary buffer for an EXISTING set of f2v
        messages: the same per-shard boundary partial sums + psum the
        superstep tail issues (``_exchange_halo``), run once outside
        the loop.  Shard-loss recovery uses this to rebuild the halo
        slot after remapping a snapshot onto a new partition — the
        double buffer must hold exactly the boundary totals of the
        snapshot's f2v messages, computed with the NEW layout's
        reduction order, or the first post-recovery superstep would
        read garbage."""
        n_bnd = graph.n_boundary
        d = graph.dmax
        if n_bnd == 0:
            return jax.device_put(
                jnp.zeros((0, d), graph.var_costs.dtype),
                NamedSharding(self.mesh, P()))
        nb = len(graph.buckets)

        def local(g, msgs):
            _, aux = _unblock_graph(g)
            return _exchange_halo(
                tuple(m[0] for m in msgs), aux, n_bnd)

        return shard_map(
            local, mesh=self.mesh,
            in_specs=(self._graph_specs(graph),
                      (P(SHARD_AXIS),) * nb),
            out_specs=P(),
            check_rep=False,
        )(graph, tuple(f2v))

    def assignment_constraint_cost(self, graph: ShardedGraph,
                                   values: jnp.ndarray) -> jnp.ndarray:
        """Global constraint cost of a GLOBAL [V] assignment on the
        partitioned graph (the segment-boundary guard's verdict
        input): values are scattered to each shard's local order and
        the per-shard factor costs psum'd."""
        ext = jnp.concatenate(
            [values.astype(jnp.int32),
             jnp.zeros((1,), jnp.int32)])
        vals_local = ext[graph.local_global]      # [S, L-1]

        def local_cost(g, vl):
            lgraph, _ = _unblock_graph(g)
            return jax.lax.psum(
                maxsum_ops.assignment_constraint_cost(lgraph, vl[0]),
                SHARD_AXIS)

        return shard_map(
            local_cost, mesh=self.mesh,
            in_specs=(self._graph_specs(graph), P(SHARD_AXIS)),
            out_specs=P(),
            check_rep=False,
        )(graph, vals_local)

    def _assemble_values(self, graph: ShardedGraph, values_sh
                         ) -> jnp.ndarray:
        """[S, V_loc] per-shard owned values → global [V] order.
        Padding owned slots scatter to the sentinel index and are
        dropped by the final slice."""
        v_loc = graph.v_loc
        owned_global = graph.local_global[:, :v_loc]
        ext = jnp.zeros((self.n_vars + 1,), jnp.int32)
        return ext.at[owned_global.reshape(-1)].set(
            values_sh.reshape(-1))[: self.n_vars]


# ----------------------- shard-loss state remap ---------------------- #


def _factor_row_maps(source_graph: CompiledFactorGraph, part):
    """Per bucket: the positions (in real-factor row order) owned by
    each shard — the inverse of build_partitioned_graph's per-shard
    row packing (``rows[fs == s]`` in order)."""
    n_vars = source_graph.n_vars
    maps = []
    for b, fs in zip(source_graph.buckets, part.factor_shard):
        ids = np.asarray(b.var_ids)
        rows = real_factor_rows(ids, n_vars)
        maps.append((rows,
                     [np.nonzero(fs == s)[0]
                      for s in range(part.n_shards)]))
    return maps


def remap_partitioned_state(source_graph: CompiledFactorGraph,
                            old_part, new_part,
                            state: ShardedMaxSumState,
                            new_graph: ShardedGraph,
                            new_ops: "ShardOps"
                            ) -> ShardedMaxSumState:
    """Map a checkpointed/validated :class:`ShardedMaxSumState` from
    one partition's blocked layout onto another's — the shard-loss
    recovery step ("remap the global state onto the new layout").

    Messages and SAME_COUNT counters live per (factor, scope slot):
    the remap gathers each bucket's per-shard blocks back to global
    real-factor row order (host numpy — the recovery path runs once
    per device loss, not per superstep) and re-packs them under the
    new factor→shard assignment; padding rows in the new layout start
    zeroed, exactly like a fresh ``init_state`` (they scatter only
    into the sentinel slot, which nothing reads).  The halo double
    buffer is NOT remapped — the new partition has a different
    boundary set — but recomputed on device from the remapped f2v
    messages (:meth:`ShardOps.recompute_halo`), so the first
    post-recovery superstep consumes exactly what the tail exchange
    of the snapshot cycle would have produced under the new layout.
    ``stable``/``cycle`` carry over (replicated scalars are
    layout-free)."""
    state_host = jax.device_get(state)
    old_maps = _factor_row_maps(source_graph, old_part)
    new_maps = _factor_row_maps(source_graph, new_part)
    new_S = new_part.n_shards

    def regather(blocked, bucket_i):
        """[S_old, Fmax_old, ...] blocked → [F_real, ...] global."""
        blocked = np.asarray(blocked)
        rows, per_shard = old_maps[bucket_i]
        out = np.zeros((rows.shape[0],) + blocked.shape[2:],
                       blocked.dtype)
        for s, sel in enumerate(per_shard):
            out[sel] = blocked[s, :sel.shape[0]]
        return out

    def reblock(global_arr, bucket_i, f_max):
        """[F_real, ...] global → [S_new, f_max, ...] blocked."""
        _, per_shard = new_maps[bucket_i]
        out = np.zeros((new_S, f_max) + global_arr.shape[1:],
                       global_arr.dtype)
        for s, sel in enumerate(per_shard):
            out[s, :sel.shape[0]] = global_arr[sel]
        return out

    def remap_field(msgs):
        remapped = []
        for i, blocked in enumerate(msgs):
            f_max = new_graph.buckets[i].var_ids.shape[1]
            remapped.append(
                reblock(regather(blocked, i), i, f_max))
        return tuple(remapped)

    shard = NamedSharding(new_ops.mesh, P(SHARD_AXIS))
    rep = NamedSharding(new_ops.mesh, P())
    put = lambda t: tuple(  # noqa: E731
        jax.device_put(m, shard) for m in t)
    placed = ShardedMaxSumState(
        v2f=put(remap_field(state_host.v2f)),
        f2v=put(remap_field(state_host.f2v)),
        v2f_count=put(remap_field(state_host.v2f_count)),
        f2v_count=put(remap_field(state_host.f2v_count)),
        halo=jax.device_put(
            np.zeros((new_graph.n_boundary, new_graph.dmax),
                     np.asarray(state_host.halo).dtype), rep),
        stable=jax.device_put(np.asarray(state_host.stable), rep),
        cycle=jax.device_put(np.asarray(state_host.cycle), rep),
    )
    halo = new_ops.recompute_halo(new_graph, placed.f2v)
    return placed._replace(halo=halo)

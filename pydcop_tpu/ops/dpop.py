"""Tensorized DPOP: level-batched UTIL/VALUE sweeps under jit.

Reference semantics: pydcop/algorithms/dpop.py:313-439 — every node
joins its assigned constraints with its children's UTIL tables and
projects its own variable out (min/max-eliminate), leaves→root; then
assignments flow root→leaves with first-optimum tie-breaking
(relations.py:1554 find_arg_optimal).

TPU-first redesign (not a translation): the reference runs one python
computation per node, enumerating assignments in dict loops.  Here the
pseudo-tree is *level-scheduled*: all nodes at the same depth are
independent, so their UTIL tables are computed in one batched XLA call
per *signature bucket*.  A node's signature is the static shape of its
join:

    (joined-shape, (axes of component 0, axes of component 1, ...))

where each component is a dense cost table over a subset of the node's
joined dims — its own unary cost vector, the constraints assigned to
it, and its children's UTIL tables.  Nodes sharing a signature (the
common case: e.g. every leaf with one binary constraint to its parent)
are stacked on a new leading batch axis and processed by ONE jitted
kernel: broadcast-add every component into the joined hypercube, then
min/max-reduce the node's own axis.  Kernels are cached per signature,
so a 10k-node tree typically compiles a handful of programs.

The VALUE sweep is host-side: it is O(separator) gathers per node with
no batchable math (each node's slice depends on its ancestors' chosen
values), so device round-trips would dominate.

Raggedness guards (SURVEY §7 hard parts): a single node whose UTIL
table exceeds ``MAX_NODE_ELEMENTS`` raises ``UtilTooLargeError``
(mirrors the reference's footprint accounting, dpop.py:80-85 /
pseudotree computation_memory); callers fall back to the host-numpy
path when the *total* work is too small to amortize device dispatch or
too large for device memory (see algorithms/dpop.py).

Cross-edge consistency (arXiv 1909.06537): before building node plans,
``cec_survivors`` prunes domain values that are *soft-dominated* — value
``a`` of variable ``x`` is removed when some earlier value ``b`` costs
no more than ``a`` under every completion of the rest of the problem,
certified by the bound  u(b) - u(a) + sum over constraints containing x
of max over other coordinates of (c[b,..] - c[a,..]) <= 0  (min mode;
reductions and inequality flip for max).  Because the dominator has a
*smaller* domain index, first-optimum tie-breaking always lands on a
surviving value, so the final assignment is bit-identical with CEC on
or off — pruning only shrinks every hypercube axis the variable touches
and thereby raises the width ceiling under ``MAX_NODE_ELEMENTS``.
"""

from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# Per-node UTIL element cap: beyond this the separator is so wide that
# the problem needs a different algorithm (or more devices), and one
# table would dominate device memory anyway.
MAX_NODE_ELEMENTS = 2 ** 26


class UtilTooLargeError(MemoryError):
    """A UTIL table exceeds the per-node element cap."""


# -- host-side compilation: tree -> level-bucketed dense components ---- #


class _NodePlan:
    """Static plan for one pseudo-tree node's UTIL computation."""

    __slots__ = (
        "name", "dims", "shape", "components", "parent", "depth",
    )

    def __init__(self, name, dims, shape, parent, depth):
        self.name = name
        self.dims = dims          # (own, sep...) variable names
        self.shape = shape        # domain sizes, same order
        self.parent = parent
        self.depth = depth
        # axes-tuple -> summed dense array (axes ascending in dims).
        self.components: Dict[Tuple[int, ...], np.ndarray] = {}

    def add_component(self, axes: Tuple[int, ...], array: np.ndarray):
        if axes in self.components:
            self.components[axes] = self.components[axes] + array
        else:
            self.components[axes] = array


def _transpose_to_axes(array: np.ndarray, positions: List[int]
                       ) -> Tuple[Tuple[int, ...], np.ndarray]:
    """Reorder ``array`` (one axis per entry of ``positions``, positions
    being indices into the node's dims) into ascending-position order."""
    order = sorted(range(len(positions)), key=lambda i: positions[i])
    axes = tuple(positions[i] for i in order)
    return axes, np.ascontiguousarray(np.transpose(array, order))


def _tree_layout(graph, survivors: Optional[Dict[str, np.ndarray]] = None):
    """Shared host-side layout pass: nodes, depths, separator sets and
    per-node (dims, shape) with survivor-shrunk domain sizes."""
    from pydcop_tpu.computations_graph.pseudotree import node_depths

    nodes = {n.name: n for n in graph.nodes}
    depth = node_depths(graph)

    # Separator sets, bottom-up: sep(n) = (U sep(children) U scopes) - n.
    sep: Dict[str, set] = {}
    for name in sorted(nodes, key=lambda n: -depth[n]):
        node = nodes[name]
        s = set()
        for c in node.constraints:
            s.update(v.name for v in c.dimensions)
        for child in node.children:
            s.update(sep[child])
        s.discard(name)
        sep[name] = s

    def dom_size(name: str) -> int:
        if survivors is not None and name in survivors:
            return int(len(survivors[name]))
        return len(nodes[name].variable.domain)

    layout: Dict[str, Tuple[Tuple[str, ...], Tuple[int, ...]]] = {}
    for name in nodes:
        # Deterministic dim order: own variable first, then separator
        # variables shallowest-first (ties by name) — ancestors of the
        # node by the pseudo-tree property.
        sep_sorted = sorted(sep[name], key=lambda v: (depth[v], v))
        dims = (name,) + tuple(sep_sorted)
        shape = tuple(dom_size(d) for d in dims)
        layout[name] = (dims, shape)
    return nodes, depth, sep, layout


def tree_stats(graph, survivors: Optional[Dict[str, np.ndarray]] = None
               ) -> Dict[str, int]:
    """Width/size accounting for a pseudo-tree *without* materializing
    any table — safe to call on arbitrarily wide problems.

    Returns node count, level count, induced width (largest separator,
    in variables), the largest per-node UTIL element count and the total
    across nodes.  Callers compare ``max_elements`` against
    ``MAX_NODE_ELEMENTS`` to decide whether exact inference is feasible
    (optionally after CEC shrinkage via ``survivors``).
    """
    nodes, depth, sep, layout = _tree_layout(graph, survivors)
    max_elements = 0
    total_elements = 0
    for name, (dims, shape) in layout.items():
        n = int(np.prod(shape, dtype=np.float64))
        max_elements = max(max_elements, n)
        total_elements += n
    return {
        "nodes": len(nodes),
        "levels": (max(depth.values()) + 1) if depth else 0,
        "induced_width": max((len(s) for s in sep.values()), default=0),
        "max_elements": max_elements,
        "total_elements": total_elements,
    }


def cec_survivors(graph, mode: str = "min", max_rounds: int = 8
                  ) -> Tuple[Dict[str, np.ndarray], Dict[str, int]]:
    """Cross-edge consistency: per-variable surviving domain indices.

    A value ``a`` is pruned when an earlier value ``b`` soft-dominates
    it: ``u(b) - u(a) + sum_c reduce_ctx(c[b] - c[a])`` is ``<= 0`` with
    ``reduce = max`` in min mode (``>= 0`` / ``min`` in max mode), the
    context ranging over current survivors of the other scope variables.
    Iterated to a bounded fixpoint — each round's shrinkage tightens the
    neighbour contexts and can unlock further pruning.

    Returns ``(survivors, meta)`` where ``survivors`` maps variable name
    to a sorted int array of original domain indices and ``meta`` holds
    ``{"rounds", "pruned", "values"}``.
    """
    nodes = {n.name: n for n in graph.nodes}
    variables = {name: node.variable for name, node in nodes.items()}

    # Every constraint is assigned to exactly one pseudo-tree node;
    # bucket the dense form by incident variable.
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    incident: Dict[str, List[Tuple[Tuple[str, ...], np.ndarray]]] = {
        name: [] for name in nodes
    }
    for node in nodes.values():
        for c in node.constraints:
            dense = NAryMatrixRelation.from_func_relation(c)
            dims = tuple(v.name for v in dense.dimensions)
            mat = np.asarray(dense.matrix, dtype=np.float64)
            for d in dims:
                incident[d].append((dims, mat))

    unary = {
        name: np.asarray(var.cost_vector(), dtype=np.float64)
        for name, var in variables.items()
    }
    survivors: Dict[str, np.ndarray] = {
        name: np.arange(len(var.domain), dtype=np.int64)
        for name, var in variables.items()
    }

    total_values = sum(len(v.domain) for v in variables.values())
    rounds = 0
    changed = True
    while changed and rounds < max_rounds:
        changed = False
        rounds += 1
        for name in sorted(nodes):
            keep_idx = survivors[name]
            k = len(keep_idx)
            if k <= 1:
                continue
            u = unary[name][keep_idx]
            # D[b, a]: certified worst-case cost(b) - cost(a) bound.
            D = u[:, None] - u[None, :]
            for dims, mat in incident[name]:
                sub = mat
                for ax, d in enumerate(dims):
                    sub = np.take(sub, survivors[d], axis=ax)
                ax_x = dims.index(name)
                sub = np.moveaxis(sub, ax_x, 0).reshape(k, -1)
                diff = sub[:, None, :] - sub[None, :, :]
                D = D + (
                    diff.max(axis=2) if mode == "min"
                    else diff.min(axis=2)
                )
            keep = np.ones(k, dtype=bool)
            for a in range(1, k):
                col = D[:a, a]
                dominated = (
                    bool((col <= 0.0).any()) if mode == "min"
                    else bool((col >= 0.0).any())
                )
                if dominated:
                    keep[a] = False
            if not keep.all():
                survivors[name] = keep_idx[keep]
                changed = True
    kept_values = sum(len(s) for s in survivors.values())
    meta = {
        "rounds": rounds,
        "pruned": total_values - kept_values,
        "values": total_values,
    }
    return survivors, meta


def compile_tree(graph, mode: str,
                 survivors: Optional[Dict[str, np.ndarray]] = None
                 ) -> Dict[str, _NodePlan]:
    """Build per-node static plans: dims, shapes, local components.

    ``graph`` is a ComputationPseudoTree; child-UTIL components are
    added level by level during the sweep (their arrays are produced by
    the previous level's kernels).  When ``survivors`` is given (from
    ``cec_survivors``) every table axis is sliced to the surviving
    domain indices before planning, so the element cap is checked
    against the *shrunk* hypercubes.
    """
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    nodes, depth, sep, layout = _tree_layout(graph, survivors)

    plans: Dict[str, _NodePlan] = {}
    for name, node in nodes.items():
        var = node.variable
        dims, shape = layout[name]
        n_elements = int(np.prod(shape, dtype=np.int64))
        if n_elements > MAX_NODE_ELEMENTS:
            raise UtilTooLargeError(
                f"UTIL table for {name} has {n_elements} elements "
                f"(> {MAX_NODE_ELEMENTS}); separator too wide"
            )
        plan = _NodePlan(name, dims, shape, node.parent, depth[name])
        pos = {d: i for i, d in enumerate(dims)}
        u = np.asarray(var.cost_vector(), dtype=np.float32)
        if survivors is not None:
            u = u[survivors[name]]
        plan.add_component((0,), u)
        for c in node.constraints:
            dense = NAryMatrixRelation.from_func_relation(c)
            mat = np.asarray(dense.matrix, dtype=np.float32)
            if survivors is not None:
                for ax, v in enumerate(dense.dimensions):
                    mat = np.take(mat, survivors[v.name], axis=ax)
            positions = [pos[v.name] for v in dense.dimensions]
            axes, arr = _transpose_to_axes(mat, positions)
            plan.add_component(axes, arr)
        plans[name] = plan
    return plans


# -- device kernels: one per signature, cached -------------------------- #

_KERNEL_CACHE: Dict[Tuple, Any] = {}


def _kernel_for(signature: Tuple) -> Any:
    """signature = (shape, axes_tuples, mode, want_util)."""
    if signature in _KERNEL_CACHE:
        return _KERNEL_CACHE[signature]
    if len(_KERNEL_CACHE) >= 512:
        # Long-lived processes solving many differently-shaped DCOPs
        # must not accumulate compiled executables without bound.
        _KERNEL_CACHE.clear()
    import jax
    import jax.numpy as jnp

    shape, axes_tuples, mode, want_util = signature
    k = len(shape)

    def kernel(*comps):
        n = comps[0].shape[0]
        acc = jnp.zeros((n,) + shape, dtype=jnp.float32)
        for comp, axes in zip(comps, axes_tuples):
            newshape = (n,) + tuple(
                shape[i] if i in axes else 1 for i in range(k)
            )
            acc = acc + comp.reshape(newshape)
        if not want_util:
            return acc, None
        util = (
            jnp.min(acc, axis=1) if mode == "min"
            else jnp.max(acc, axis=1)
        )
        return acc, util

    _KERNEL_CACHE[signature] = jax.jit(kernel)
    return _KERNEL_CACHE[signature]


def solve_sweep(graph, mode: str = "min", cec: bool = False,
                call: Optional[Any] = None,
                precomputed_survivors: Optional[Tuple] = None
                ) -> Tuple[Dict[str, Any], Dict[str, int]]:
    """Run the full DPOP solve with level-batched jitted kernels.

    ``cec`` enables cross-edge consistency preprocessing (assignment is
    bit-identical either way; tables shrink).  ``call`` is an optional
    invocation hook ``call(signature, kernel, *stacked) -> kernel_out``
    — engine tiers pass ``timed_jit_call`` wrappers here so compile/run
    accounting, tracing and efficiency ledgers see every dispatch.
    ``precomputed_survivors`` short-circuits the (host-heavy) dominance
    pass with a cached ``cec_survivors`` result for repeat solves of a
    static problem.

    Returns (assignment, stats).
    """
    survivors = None
    cec_meta = {"rounds": 0, "pruned": 0, "values": 0}
    if cec:
        if precomputed_survivors is not None:
            survivors, cec_meta = precomputed_survivors
        else:
            survivors, cec_meta = cec_survivors(graph, mode)
    plans = compile_tree(graph, mode, survivors=survivors)
    nodes = {n.name: n for n in graph.nodes}
    by_level: Dict[int, List[str]] = defaultdict(list)
    for name, plan in plans.items():
        by_level[plan.depth].append(name)
    max_depth = max(by_level) if by_level else 0

    joined: Dict[str, np.ndarray] = {}
    n_kernel_calls = 0
    msg_count = 0
    msg_size = 0

    # UTIL sweep, deepest level first; each level is one batched kernel
    # call per signature bucket.
    for level in range(max_depth, -1, -1):
        buckets: Dict[Tuple, List[str]] = defaultdict(list)
        for name in by_level[level]:
            plan = plans[name]
            axes_tuples = tuple(sorted(plan.components))
            want_util = plan.parent is not None
            key = (plan.shape, axes_tuples, mode, want_util)
            buckets[key].append(name)
        for key, names in sorted(buckets.items()):
            shape, axes_tuples, _, want_util = key
            stacked = [
                np.stack(
                    [plans[n].components[axes] for n in names]
                )
                for axes in axes_tuples
            ]
            kernel = _kernel_for(key)
            if call is None:
                acc, util = kernel(*stacked)
            else:
                acc, util = call(key, kernel, *stacked)
            n_kernel_calls += 1
            acc_np = np.asarray(acc)
            util_np = None if util is None else np.asarray(util)
            for i, name in enumerate(names):
                plan = plans[name]
                joined[name] = acc_np[i]
                if want_util:
                    parent_plan = plans[plan.parent]
                    ppos = {
                        d: j for j, d in enumerate(parent_plan.dims)
                    }
                    positions = [ppos[d] for d in plan.dims[1:]]
                    axes, arr = _transpose_to_axes(
                        util_np[i], positions
                    )
                    parent_plan.add_component(axes, arr)
                    msg_count += 1
                    msg_size += arr.size

    # VALUE sweep, root level down: slice on ancestors' values, pick
    # the first optimum (reference find_arg_optimal order).  With CEC
    # active, table axes index *surviving* values, so ancestor values
    # map through the survivor list and the chosen row maps back to the
    # original domain.
    assignment: Dict[str, Any] = {}
    chosen_pos: Dict[str, int] = {}
    argopt = np.argmin if mode == "min" else np.argmax
    for level in range(0, max_depth + 1):
        for name in sorted(by_level[level]):
            plan = plans[name]
            var = nodes[name].variable
            idx = tuple(chosen_pos[d] for d in plan.dims[1:])
            vec = joined[name][(slice(None),) + idx]
            pos = int(argopt(vec))
            orig = pos if survivors is None else int(survivors[name][pos])
            chosen_pos[name] = pos
            assignment[name] = var.domain[orig]
            msg_count += len(nodes[name].children)
    stats = {
        "msg_count": msg_count,
        "msg_size": msg_size,
        "kernel_calls": n_kernel_calls,
        "levels": max_depth + 1,
        "cec_rounds": cec_meta["rounds"],
        "cec_pruned": cec_meta["pruned"],
    }
    return assignment, stats


def var_index(variable, value) -> int:
    return variable.domain.index(value)

"""Serve-smoke gate: end-to-end proof of the solve service's batching.

Part of ``make test`` (like ``make trace-demo`` / ``make perf-smoke``).
Starts the real service on port 0 and drives it over HTTP:

1. **Coalescing + parity**: a concurrent burst of N same-structure
   requests (plus a second structure mixed in) must complete in FEWER
   than N device dispatches (batch-coalescing counters asserted), at
   least one dispatch must be multi-instance, the two structures must
   never share a dispatch (dispatch count >= 2), and EVERY response's
   assignment must equal the equivalent solo ``api.solve`` run.
2. **Overload**: with a tiny high-water mark and a slowed dispatch,
   a burst past the queue bound must yield 429s — not a hang and not
   a dropped request: every accepted request finishes, every rejected
   one is a clean 429, and ``pydcop_requests_total{status}`` accounts
   for every single request fired.

Run:  python tools/serve_smoke.py      (exit 0 = all claims hold)
"""

import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

SAME_STRUCTURE_BURST = 8
OTHER_STRUCTURE_BURST = 3
MAX_CYCLES = 120
OVERLOAD_BURST = 10


def build_instance(n_vars: int, seed: int):
    """Small random-cost ring coloring; same ``n_vars`` -> same
    structure bin, different seeds -> different cost tables."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("colors", "", [0, 1, 2])
    dcop = DCOP(f"smoke_{n_vars}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    for k, (i, j) in enumerate(
            [(i, (i + 1) % n_vars) for i in range(n_vars)]):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def post(url: str, body: dict):
    req = urllib.request.Request(
        url + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def scrape_requests_total(url: str) -> dict:
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    out = {}
    for line in text.splitlines():
        m = re.match(
            r'pydcop_requests_total\{status="([^"]+)"\} (\S+)', line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


def check(cond, message):
    if not cond:
        print(f"serve_smoke: FAIL — {message}", file=sys.stderr)
        sys.exit(1)
    print(f"serve_smoke: ok — {message}")


def leg_coalescing():
    from pydcop_tpu import api

    handle = api.serve(port=0, batch_window_s=0.3, max_batch=16,
                       max_queue=64)
    try:
        url = handle.url
        dcops = (
            [build_instance(12, seed)
             for seed in range(SAME_STRUCTURE_BURST)]
            + [build_instance(9, 100 + seed)
               for seed in range(OTHER_STRUCTURE_BURST)]
        )
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        payloads = [dcop_yaml(d) for d in dcops]
        results = [None] * len(dcops)

        def client(i):
            results[i] = post(url, {
                "dcop": payloads[i], "wait": True, "timeout": 120,
                "params": {"max_cycles": MAX_CYCLES},
            })

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(dcops))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        check(all(r is not None and r[0] == 200
                  and r[1]["status"] == "FINISHED" for r in results),
              f"all {len(dcops)} burst responses valid")

        stats = handle.service.stats()
        n = len(dcops)
        check(stats["dispatches"] < n,
              f"{n} requests took {stats['dispatches']} device "
              f"dispatches (< {n}: batching coalesced)")
        check(stats["batched_dispatches"] >= 1,
              ">= 1 multi-instance batch dispatched "
              f"({stats['batched_dispatches']})")
        check(stats["dispatches"] >= 2,
              "two structures dispatched separately "
              f"({stats['dispatches']} dispatches)")

        # Every response must match the equivalent solo api.solve.
        for dcop, (_, res) in zip(dcops, results):
            solo = api.solve(dcop, "maxsum", backend="device",
                             max_cycles=MAX_CYCLES)
            if res["assignment"] != solo["assignment"]:
                check(False,
                      f"served assignment for {dcop.name} differs "
                      "from solo api.solve")
        check(True,
              f"all {len(dcops)} served assignments identical to "
              "solo api.solve")
    finally:
        handle.stop()


def leg_overload():
    from pydcop_tpu import api

    handle = api.serve(port=0, batch_window_s=0.01, max_batch=2,
                       max_queue=32, high_water=3)
    try:
        url = handle.url
        # Slow the device call down so the burst genuinely overruns
        # the queue (an unthrottled CPU dispatch drains too fast to
        # ever hit the high-water mark on a quiet box).
        service = handle.service
        real_run = service._run_batch

        def slowed(reqs, params):
            time.sleep(0.25)
            return real_run(reqs, params)

        service._run_batch = slowed
        before = scrape_requests_total(url)
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        statuses = [None] * OVERLOAD_BURST
        payloads = [dcop_yaml(build_instance(10, 200 + i))
                    for i in range(OVERLOAD_BURST)]

        def client(i):
            statuses[i] = post(url, {
                "dcop": payloads[i],
                "params": {"max_cycles": 40},
            })

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(OVERLOAD_BURST)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        check(all(s is not None for s in statuses),
              "no overload request hung (all POSTs returned)")
        accepted = [s for s in statuses if s[0] == 202]
        rejected = [s for s in statuses if s[0] == 429]
        check(not [s for s in statuses if s[0] not in (202, 429)],
              "overload responses are only 202 or 429")
        check(len(rejected) >= 1,
              f"queue past high-water yielded 429s "
              f"({len(rejected)}/{OVERLOAD_BURST})")
        # Every accepted request must finish — none dropped.
        deadline = time.monotonic() + 60
        for _, body in accepted:
            rid = body["id"]
            while time.monotonic() < deadline:
                result = handle.service.result(rid, wait=1.0)
                if result is not None:
                    break
            check(result is not None
                  and result["status"] == "FINISHED",
                  f"accepted request {rid} completed")
        after = scrape_requests_total(url)
        delta_ok = after.get("ok", 0) - before.get("ok", 0)
        delta_rej = (after.get("rejected_queue_full", 0)
                     - before.get("rejected_queue_full", 0))
        check(delta_ok == len(accepted)
              and delta_rej == len(rejected)
              and delta_ok + delta_rej == OVERLOAD_BURST,
              "pydcop_requests_total accounts for every request "
              f"(ok {delta_ok:.0f} + 429 {delta_rej:.0f} = "
              f"{OVERLOAD_BURST})")
    finally:
        handle.stop()


def main() -> int:
    t0 = time.perf_counter()
    leg_coalescing()
    leg_overload()
    print(f"serve_smoke: PASS ({time.perf_counter() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

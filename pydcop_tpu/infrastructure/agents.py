"""Agent runtime: one thread per agent hosting N computations.

Reference parity: pydcop/infrastructure/agents.py (Agent :78 — thread
:140, add_computation :175, run/start :324, main loop _run :785-838,
clean_shutdown :431, metrics :717, set_periodic_action :743;
AgentMetrics :878; ResilientAgent :927).
"""

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from pydcop_tpu.dcop.objects import AgentDef
from pydcop_tpu.infrastructure.communication import (
    CommunicationLayer,
    Messaging,
)
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
)
from pydcop_tpu.infrastructure.discovery import Discovery
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer


class AgentException(Exception):
    pass


class Agent:
    """A container running computations on its own thread.

    The agent pops messages from its Messaging priority queue, dispatches
    them to hosted computations, and runs registered periodic actions in
    between (reference loop: agents.py:785-838).
    """

    def __init__(self, name: str, comm: CommunicationLayer,
                 agent_def: Optional[AgentDef] = None,
                 delay: Optional[float] = None,
                 ui_port: Optional[int] = None):
        self._name = name
        self.agent_def = agent_def
        self._comm = comm
        self._messaging = Messaging(name, comm, delay=delay or 0)
        self.discovery = Discovery(name, comm.address)
        comm.discovery = self.discovery
        self.discovery.agent_change_hooks.append(comm.on_agent_change)
        self._computations: Dict[str, MessagePassingComputation] = {}
        self._thread = threading.Thread(
            target=self._run, name=f"agent_{name}", daemon=True
        )
        self._running = False
        self._stopping = threading.Event()
        self.logger = logging.getLogger(f"pydcop.agent.{name}")
        self._periodic: List[List] = []  # [period, action, next_due]
        self.t_active = 0.0
        self._start_time: Optional[float] = None
        # Activity accounting: the hot message loop bumps plain
        # instance attributes (no shared locks — the disabled-cost
        # contract), and :meth:`_publish_metrics` folds the deltas
        # into the process-wide registry counters whenever metrics are
        # read — the registry stays the canonical, monotone export
        # (a re-created agent name keeps accumulating the same
        # series) while per-instance figures come from the local
        # attributes.
        self._n_handled = 0
        self._bytes_in = 0
        self._m_handled = metrics_registry.counter(
            "pydcop_agent_messages_handled_total",
            "Messages handled by the agent thread").bind(agent=name)
        self._m_in_bytes = metrics_registry.counter(
            "pydcop_agent_message_bytes_handled_total",
            "Total size of messages handled by the agent thread"
        ).bind(agent=name)
        self._m_active = metrics_registry.counter(
            "pydcop_agent_active_seconds_total",
            "Seconds the agent thread spent handling messages"
        ).bind(agent=name)
        # Already-published portion of the local attributes.
        self._m_published = [0, 0, 0.0]
        # Orchestration hooks, set by OrchestratedAgent:
        self.on_value_change: Optional[Callable] = None
        self.on_cycle_change: Optional[Callable] = None
        self.on_computation_finished: Optional[Callable] = None
        self.add_computation(self.discovery.discovery_computation)
        # Optional live-observability websocket server (ui.py).
        self.ui_server = None
        if ui_port:
            from pydcop_tpu.infrastructure.ui import UiServer

            self.ui_server = UiServer(self, ui_port)
            self.ui_server.start()

    # -- properties ---------------------------------------------------- #

    @property
    def name(self) -> str:
        return self._name

    @property
    def address(self):
        return self._comm.address

    @property
    def messaging(self) -> Messaging:
        return self._messaging

    @property
    def is_running(self) -> bool:
        return self._running

    @property
    def computations(self) -> List[MessagePassingComputation]:
        return list(self._computations.values())

    def computation(self, name: str) -> MessagePassingComputation:
        try:
            return self._computations[name]
        except KeyError:
            raise AgentException(
                f"Agent {self.name} does not host computation {name}"
            )

    def has_computation(self, name: str) -> bool:
        return name in self._computations

    # -- computations -------------------------------------------------- #

    def add_computation(self, computation: MessagePassingComputation,
                        comp_name: Optional[str] = None):
        """Host a computation: wire its message sender to our queue,
        register it in messaging + discovery, and hook notifications
        (reference agents.py:175-221)."""
        name = comp_name or computation.name
        computation.message_sender = self._messaging.post_msg
        computation._periodic_action_handler = self._add_periodic
        computation._periodic_remove_handler = self.remove_periodic_action
        for period, _action, guarded in computation._periodic_actions:
            # Run the pause-guarded wrapper, not the raw action.
            self._add_periodic(period, guarded)
        self._computations[name] = computation
        self._messaging.register_computation(name)
        if not name.startswith("_"):
            self.discovery.register_computation(name, self._name)
        computation._on_value_cb = self._notify_value
        computation._on_cycle_cb = self._notify_cycle
        computation._on_finish_cb = self._notify_finished

    def remove_computation(self, name: str):
        comp = self._computations.pop(name, None)
        if comp is not None:
            comp.stop()
            # Drop its periodic wrappers from our schedule — otherwise
            # they keep firing for a computation we no longer host
            # (e.g. an ADSA tick after repair migrated it away).
            for _period, _action, guarded in comp._periodic_actions:
                self.remove_periodic_action(guarded)
            comp._periodic_action_handler = None
            comp._periodic_remove_handler = None
            self._messaging.unregister_computation(name)
            if not name.startswith("_"):
                self.discovery.unregister_computation(name)

    def _notify_value(self, comp):
        if self.on_value_change:
            self.on_value_change(comp)

    def _notify_cycle(self, comp):
        if self.on_cycle_change:
            self.on_cycle_change(comp)

    def _notify_finished(self, comp):
        if self.on_computation_finished:
            self.on_computation_finished(comp)

    # -- periodic actions ---------------------------------------------- #

    def _add_periodic(self, period: float, action: Callable):
        self._periodic.append([period, action, time.monotonic() + period])

    def set_periodic_action(self, period: float, action: Callable):
        """Run `action` every `period` seconds on the agent thread
        (reference agents.py:743)."""
        self._add_periodic(period, action)
        return action

    def remove_periodic_action(self, action):
        self._periodic = [p for p in self._periodic if p[1] is not action]

    # -- lifecycle ----------------------------------------------------- #

    def start(self):
        if self._running:
            raise AgentException(f"Agent {self.name} already started")
        self._running = True
        self._start_time = time.monotonic()
        self._thread.start()

    def run(self, computations: Optional[List[str]] = None):
        """Start hosted computations (all non-service ones by default)."""
        if computations is None:
            computations = [
                n for n in self._computations if not n.startswith("_")
            ]
        for name in computations:
            comp = self.computation(name)
            if not comp.is_running:
                comp.start()

    def _run(self):
        from pydcop_tpu.infrastructure import stats

        while not self._stopping.is_set():
            cmsg = self._messaging.next_msg(0.05)
            if cmsg is not None:
                t0 = time.monotonic()
                if tracer.enabled:
                    tracer.instant(
                        "message_recv", "comm", agent=self._name,
                        computation=cmsg.dest_comp, src=cmsg.src_comp,
                        type=cmsg.msg.type, size=cmsg.msg.size,
                    )
                    with tracer.span(
                            "agent_step", "agent", agent=self._name,
                            computation=cmsg.dest_comp,
                            msg_type=cmsg.msg.type):
                        self._handle_message(cmsg)
                else:
                    self._handle_message(cmsg)
                duration = time.monotonic() - t0
                self.t_active += duration
                self._n_handled += 1
                self._bytes_in += cmsg.msg.size
                if stats.tracing_enabled():
                    comp = self._computations.get(cmsg.dest_comp)
                    stats.trace_computation(
                        cmsg.dest_comp, duration,
                        msg_in_count=1, msg_in_size=cmsg.msg.size,
                        value=getattr(comp, "current_value", None),
                    )
            self._process_periodic()

    def _handle_message(self, cmsg):
        comp = self._computations.get(cmsg.dest_comp)
        if comp is None:
            self.logger.warning(
                "Message for unknown computation %s: %s",
                cmsg.dest_comp, cmsg.msg,
            )
            return
        try:
            comp.on_message(cmsg.src_comp, cmsg.msg, time.monotonic())
        except Exception:
            self.logger.exception(
                "Error handling message %s for %s", cmsg.msg, cmsg.dest_comp
            )

    def _process_periodic(self):
        now = time.monotonic()
        for entry in self._periodic:
            period, action, due = entry
            if now >= due:
                entry[2] = now + period
                try:
                    action()
                except Exception:
                    self.logger.exception("Error in periodic action")

    def stop(self):
        self._stopping.set()

    def clean_shutdown(self, timeout: float = 5):
        """Stop computations, drain, stop the thread and transport."""
        for comp in list(self._computations.values()):
            try:
                comp.stop()
            except Exception:
                self.logger.exception(
                    "Error stopping computation %s", comp.name
                )
        self.stop()
        self.join(timeout)
        if self.ui_server is not None:
            self.ui_server.stop()
        self._messaging.shutdown()

    def join(self, timeout: Optional[float] = None):
        if self._thread.is_alive():
            self._thread.join(timeout)

    # -- metrics ------------------------------------------------------- #

    def _publish_metrics(self):
        """Fold the hot-loop attribute deltas into the registry
        counters; returns this instance's (handled, bytes_in,
        active_s) totals."""
        handled, in_size, active = (
            self._n_handled, self._bytes_in, self.t_active)
        delta = (handled - self._m_published[0],
                 in_size - self._m_published[1],
                 active - self._m_published[2])
        self._m_published = [handled, in_size, active]
        if delta[0]:
            self._m_handled.inc(delta[0])
        if delta[1]:
            self._m_in_bytes.inc(delta[1])
        if delta[2] > 0:
            self._m_active.inc(delta[2])
        return handled, in_size, active

    def metrics(self) -> Dict:
        """Reference-parity agent metrics (agents.py:717), extended
        with message-size totals and the activity-time split — all
        sourced from the observability metrics registry, so
        ``pydcop run --run_metrics`` and the orchestrator's
        end-metrics aggregate the exact same counters."""
        cycles = {}
        for name, comp in self._computations.items():
            if hasattr(comp, "cycle_count"):
                cycles[name] = comp.cycle_count
        handled, in_size, active = self._publish_metrics()
        total = (
            time.monotonic() - self._start_time
            if self._start_time else 0.0
        )
        out_count, out_size = self._messaging.ext_msg_totals()
        return {
            "count_ext_msg": dict(self._messaging.count_ext_msg),
            "size_ext_msg": dict(self._messaging.size_ext_msg),
            "cycles": cycles,
            "activity_ratio": active / total if total else 0,
            "msg_count": out_count,
            "msg_size": out_size,
            "msg_in_count": handled,
            "msg_in_size": in_size,
            "activity": {
                "active_s": active,
                "idle_s": max(total - active, 0.0),
                "total_s": total,
            },
        }

    def __repr__(self):
        return f"Agent({self.name})"

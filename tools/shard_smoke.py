"""Shard-smoke gate: the partitioned engine's claims, on CPU.

Part of ``make test`` (like ``make chaos`` / ``make perf-smoke``):
quick, deterministic checks that the sharded superstep actually is
what ISSUE 7 says it is —

1. **Cut quality**: the min-edge-cut partitioner on a ~2k-variable
   locally-connected loopy graph (a 45x45 grid coloring) lands
   ``edge_cut_fraction`` < 0.3 over 8 shards with balance within the
   cap (measured ~0.02 here — grids partition well; the 0.3 bound is
   the acceptance criterion's regime marker).
2. **Communication accounting**: the per-superstep halo exchange
   volume (``[B, D]`` boundary buffer) is STRICTLY below the
   replicated path's dense ``[V+1, D]`` all-reduce volume.
3. **Parity**: the 8-shard solve produces the identical assignment
   (and therefore identical host-evaluated cost) as the unsharded
   single-device engine at the same cycle budget.
4. **Auto-padding regression**: ``shard_graph`` on a bucket whose row
   count is NOT divisible by the mesh size pads instead of raising.

Runs under 8 forced host devices
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``), the same
recipe CI parity tests use, so the gate needs no accelerator.

Run:  python tools/shard_smoke.py      (exit 0 = all claims hold)
"""

import os
import sys
import time

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

N_SHARDS = 8
GRID_SIDE = 45          # 2025 variables, 3960 factors — loopy
MAX_CYCLES = 80


def fail(msg: str) -> "None":
    print(f"shard_smoke: FAIL — {msg}")
    sys.exit(1)


def main() -> int:
    t0 = time.perf_counter()
    import jax

    if len(jax.devices()) < N_SHARDS:
        fail(f"only {len(jax.devices())} devices (forced-host flag "
             "not honored?)")

    from bench import build_grid_dcop
    from pydcop_tpu.engine.compile import compile_dcop
    from pydcop_tpu.engine.runner import (
        MaxSumEngine,
        ShardedMaxSumEngine,
    )
    from pydcop_tpu.engine.sharding import make_mesh, shard_graph

    dcop = build_grid_dcop(GRID_SIDE)
    graph, meta = compile_dcop(dcop, noise_level=0.01)

    single = MaxSumEngine(graph, meta)
    res1 = single.run(max_cycles=MAX_CYCLES, stop_on_convergence=False)

    sharded = ShardedMaxSumEngine(graph, meta, n_shards=N_SHARDS)
    m = sharded.extra_metrics
    cut = m["edge_cut_fraction"]
    if not cut < 0.3:
        fail(f"edge_cut_fraction {cut:.3f} >= 0.3 on a grid — the "
             "partitioner regressed")
    halo = m["halo_exchange_elems_per_superstep"]
    repl = m["replicated_allreduce_elems_per_superstep"]
    if not halo < repl:
        fail(f"halo exchange volume {halo} not below the replicated "
             f"all-reduce volume {repl}")
    res8 = sharded.run(max_cycles=MAX_CYCLES, stop_on_convergence=False)
    if res8.assignment != res1.assignment:
        diff = sum(res8.assignment[k] != res1.assignment[k]
                   for k in res1.assignment)
        fail(f"sharded assignment diverged on {diff}/"
             f"{len(res1.assignment)} variables")
    cost1, _ = dcop.solution_cost(res1.assignment)
    cost8, _ = dcop.solution_cost(res8.assignment)
    if cost1 != cost8:
        fail(f"sharded cost {cost8} != unsharded {cost1}")

    # Auto-padding regression: 1001 binary factors do not divide 8.
    from pydcop_tpu.engine.compile import compile_factor_graph

    sub = list(dcop.constraints.values())[:1001]
    g_odd, _ = compile_factor_graph(
        list(dcop.variables.values()), sub)
    mesh = make_mesh(N_SHARDS)
    placed = shard_graph(g_odd, mesh)
    rows = placed.buckets[0].costs.shape[0]
    if rows % N_SHARDS:
        fail(f"shard_graph left {rows} rows, not a multiple of "
             f"{N_SHARDS}")

    print(
        f"shard_smoke: OK — {GRID_SIDE * GRID_SIDE} vars / "
        f"{len(dcop.constraints)} factors over {N_SHARDS} shards: "
        f"edge_cut={cut:.3f}, halo {halo} elems/superstep vs "
        f"replicated {repl} ({halo / repl:.1%}), bit-parity at "
        f"{MAX_CYCLES} cycles (cost {cost8}), autopad {rows} rows "
        f"[{time.perf_counter() - t0:.1f}s]"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""DSA (Distributed Stochastic Algorithm) step kernel — variants A/B/C.

Reference parity: pydcop/algorithms/dsa.py:214-431 (Zhang et al. 2005
semantics): per cycle each variable computes its best local response
given neighbors' previous values; it changes (to a uniform-random choice
among optimal values) with probability p when

- variant A: strict improvement exists (delta > 0, :358);
- variant B: delta > 0, or delta == 0 with some incident constraint not
  at its own optimum (:369, exists_violated_constraint :419) — dropping
  the current value from the candidates when other optima exist (:380);
- variant C: delta >= 0 (:389), same current-value dropping.

The whole population updates in lockstep from previous-cycle values,
matching the reference's current/next cycle maps (:266-268).
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import CompiledFactorGraph
from pydcop_tpu.ops.localsearch import (
    assignment_cost,
    best_candidates,
    candidate_costs,
    factor_current_costs,
    random_best_choice,
    random_initial_values,
)


class DsaState(NamedTuple):
    values: jnp.ndarray  # [V+1] int32 current value index (sentinel last)
    key: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph, seed: int = 0) -> DsaState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return DsaState(
        values=random_initial_values(k0, graph),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _factor_optima(graph: CompiledFactorGraph) -> Tuple[jnp.ndarray, ...]:
    """Per bucket, each factor's optimal (min) cost over all assignments
    (reference best_constraints_costs, dsa.py:273)."""
    return tuple(
        jnp.min(b.costs, axis=tuple(range(1, b.costs.ndim)))
        for b in graph.buckets
    )


def violated_vars(graph: CompiledFactorGraph,
                  values: jnp.ndarray) -> jnp.ndarray:
    """[V+1] bool: has an incident constraint not at its optimal cost
    (reference exists_violated_constraint, dsa.py:419)."""
    n_segments = graph.var_costs.shape[0]
    out = jnp.zeros((n_segments,), dtype=jnp.int32)
    for bucket, cur, opt in zip(
        graph.buckets, factor_current_costs(graph, values),
        _factor_optima(graph),
    ):
        viol = (cur != opt).astype(jnp.int32)
        for p in range(bucket.var_ids.shape[1]):
            out = jnp.maximum(out, jax.ops.segment_max(
                viol, bucket.var_ids[:, p], num_segments=n_segments
            ))
    return out > 0


def dsa_step(state: DsaState, graph: CompiledFactorGraph, *,
             variant: str, probability: jnp.ndarray) -> DsaState:
    """One lockstep DSA cycle.  `probability` is scalar or [V+1]
    (per-variable, for p_mode=arity)."""
    key, k_choice, k_change = jax.random.split(state.key, 3)
    values = state.values

    cand = candidate_costs(graph, values)               # [V+1, D]
    cur = jnp.take_along_axis(cand, values[:, None], axis=1).squeeze(1)
    best, is_best = best_candidates(graph, cand)
    delta = cur - best                                   # >= 0

    if variant == "A":
        eligible = delta > 0
        choice_mask = is_best
    else:
        n_best = jnp.sum(is_best, axis=1)
        one_hot_cur = (
            jnp.arange(cand.shape[1])[None, :] == values[:, None]
        )
        drop_cur = ((delta == 0) & (n_best > 1))[:, None] & one_hot_cur
        choice_mask = is_best & ~drop_cur
        if variant == "B":
            eligible = (delta > 0) | (
                (delta == 0) & violated_vars(graph, values)
            )
        else:  # C
            eligible = delta >= 0

    new_vals = random_best_choice(k_choice, choice_mask)
    u = jax.random.uniform(k_change, (values.shape[0],))
    change = eligible & (u < probability)
    values = jnp.where(change, new_vals, values)
    return DsaState(values=values, key=key, cycle=state.cycle + 1)


def run_dsa(graph: CompiledFactorGraph, max_cycles: int, *,
            variant: str = "B", probability=0.7, seed: int = 0,
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full DSA run in one XLA program.

    Returns (values [V], final cost, cycles)."""
    state = init_state(graph, seed)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: dsa_step(
            s, graph, variant=variant, probability=probability
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle

"""oilp_secp_cgdp: optimal ILP, SECP flavor, constraint graph.

Reference parity: pydcop/distribution/oilp_secp_cgdp.py — SECP
preferences come in through hosting costs; the weighted ILP model
applies unchanged.
"""

from pydcop_tpu.distribution.ilp_compref import (  # noqa: F401
    distribute,
    distribution_cost,
)

"""``pydcop trace``: inspect, merge and compare trace files.

``pydcop trace summary FILE`` prints top-k span aggregates (count,
total/mean/max duration) from a Chrome ``trace_event`` JSON or a JSONL
trace — the quick "where did the time go" answer that does not need a
browser (``--json`` emits the same rows machine-readably, the input
side of ``trace diff`` and CI assertions).  Instant events (fault
injections, breaker trips, message sends) aggregate with zero
duration; their counts are the point.

``pydcop trace query --request ID FILE [FILE...]`` reconstructs ONE
request's span tree out of a trace: every span/instant tagged with
the request's ``trace_id`` (directly, or via a dispatch's
``trace_ids`` batch tag) is filtered out and re-nested by time
containment per lane, then stitched under one root ordered by time —
the submit, queue wait, serve dispatch and engine segments of a
single request, even when they crossed threads or processes
(multiple files are clock-anchor aligned like ``merge``).  The
trace_id comes from the submit ack (HTTP ``trace_id`` field), a
latency-histogram exemplar, or ``/stats``.

``pydcop trace merge OUT IN1 IN2 ...`` aligns N per-process traces on
one wall-clock axis (each exported trace carries a monotonic-to-wall
anchor in its header; offsets are corrected per file) and namespaces
their thread lanes, producing one Chrome trace for the whole
multi-process run.  ``pydcop trace diff A B`` compares two traces
span-name by span-name (count/total/p50 deltas) and exits 1 when a
span regressed beyond ``--threshold`` — the trace-level counterpart
of the bench sentinel.

All subcommands print a one-line error (exit 2) instead of a
traceback on empty/truncated/non-trace files.
"""

import json
import sys


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "trace", help="inspect, merge and compare trace files")
    trace_sub = parser.add_subparsers(
        title="trace commands", dest="trace_command")

    summary = trace_sub.add_parser(
        "summary", help="top-k span aggregates of a trace file")
    summary.add_argument("trace_file", help="chrome-JSON or JSONL "
                                            "trace file")
    summary.add_argument("--top", type=int, default=15,
                         help="rows to print (default 15)")
    summary.add_argument("--by", default="name",
                         choices=["name", "cat"],
                         help="aggregate by span name or category")
    summary.add_argument("--json", action="store_true",
                         dest="as_json",
                         help="emit the summary as one JSON document "
                              "(machine-readable; used by trace diff "
                              "pipelines and CI)")
    summary.set_defaults(func=run_summary)

    merge = trace_sub.add_parser(
        "merge", help="merge N per-process traces into one aligned "
                      "Chrome trace")
    merge.add_argument("out_file", help="merged Chrome-trace output")
    merge.add_argument("trace_files", nargs="+",
                       help="two or more input traces (chrome or "
                            "jsonl; clock-anchor headers align them)")
    merge.set_defaults(func=run_merge)

    diff = trace_sub.add_parser(
        "diff", help="per-span-name count/total/p50 deltas between "
                     "two traces")
    diff.add_argument("trace_a", help="baseline trace")
    diff.add_argument("trace_b", help="candidate trace")
    diff.add_argument("--threshold", type=float, default=0.25,
                      help="relative total-duration growth that "
                           "flags a regression (default 0.25)")
    diff.add_argument("--min_delta_ms", type=float, default=1.0,
                      help="absolute growth floor below which a span "
                           "never flags (default 1 ms)")
    diff.add_argument("--json", action="store_true", dest="as_json",
                      help="emit the full diff rows as JSON")
    diff.set_defaults(func=run_diff)

    query = trace_sub.add_parser(
        "query", help="one request's span tree out of a trace "
                      "(filter by trace_id, re-nest, print)")
    query.add_argument("trace_files", nargs="+",
                       help="one or more trace files (several are "
                            "clock-anchor aligned like merge)")
    query.add_argument("--request", required=True, metavar="TRACE_ID",
                       help="the request's trace_id (from the submit "
                            "ack, a latency exemplar, or /stats)")
    query.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the reconstructed tree as JSON")
    query.set_defaults(func=run_query)

    parser.set_defaults(func=_no_subcommand(parser))


def _no_subcommand(parser):
    def run(_args) -> int:
        parser.print_help(sys.stderr)
        return 2

    return run


def _load(path):
    """load_trace_file with the command-level error contract."""
    from pydcop_tpu.observability.trace import (
        TraceFileError,
        load_trace_file,
    )

    try:
        return load_trace_file(path)
    except TraceFileError as exc:
        print(f"pydcop trace: {exc}", file=sys.stderr)
        return None


def run_summary(args) -> int:
    from pydcop_tpu.observability.trace import summarize_spans

    events = _load(args.trace_file)
    if events is None:
        return 2
    rows = summarize_spans(events, by=args.by, top=args.top)
    spans = sum(1 for e in events if e.get("ph") == "X")
    instants = sum(1 for e in events if e.get("ph") == "i")
    threads = len({e.get("tid") for e in events})
    if args.as_json:
        print(json.dumps({
            "file": args.trace_file,
            "spans": spans,
            "instants": instants,
            "threads": threads,
            "by": args.by,
            "rows": rows,
        }))
        return 0
    print(f"{args.trace_file}: {spans} spans, {instants} instants, "
          f"{threads} threads")
    if not rows:
        print("no span events")
        return 0
    key_width = max(len(str(r[args.by])) for r in rows)
    key_width = max(key_width, len(args.by))
    header = (f"{args.by:<{key_width}}  {'count':>8}  "
              f"{'total_ms':>12}  {'mean_ms':>10}  {'max_ms':>10}")
    print(header)
    print("-" * len(header))
    for r in rows:
        print(f"{str(r[args.by]):<{key_width}}  {r['count']:>8}  "
              f"{r['total_ms']:>12.3f}  {r['mean_ms']:>10.3f}  "
              f"{r['max_ms']:>10.3f}")
    return 0


def run_merge(args) -> int:
    from pydcop_tpu.observability.trace import (
        TraceFileError,
        merge_traces,
    )

    try:
        info = merge_traces(args.trace_files, args.out_file)
    except TraceFileError as exc:
        print(f"pydcop trace: {exc}", file=sys.stderr)
        return 2
    align_note = (
        "wall-clock aligned" if info["aligned"]
        else f"{info['anchored']}/{info['files']} anchored — "
             "NOT aligned, each file rebased to its own start"
    )
    print(
        f"{args.out_file}: merged {info['files']} traces "
        f"({align_note}) -> {info['events']} events on "
        f"{info['lanes']} lanes, {info['span_us'] / 1000.0:.1f} ms "
        "span"
    )
    return 0


def run_query(args) -> int:
    from pydcop_tpu.observability.trace import (
        TraceFileError,
        load_events_aligned,
        query_request,
    )

    try:
        events = load_events_aligned(args.trace_files)
    except TraceFileError as exc:
        print(f"pydcop trace: {exc}", file=sys.stderr)
        return 2
    tree = query_request(events, args.request)
    if args.as_json:
        print(json.dumps(tree))
        return 0 if tree["events"] else 1
    if not tree["events"]:
        print(f"no events tagged trace_id={args.request!r} in "
              f"{len(args.trace_files)} file(s)", file=sys.stderr)
        return 1
    nesting = ("well-nested" if tree["well_nested"]
               else "NOT WELL-NESTED (corrupt or mis-merged trace?)")
    print(f"request {args.request}: {tree['spans']} spans, "
          f"{tree['instants']} instants on {tree['lanes']} lane(s), "
          f"{nesting}")

    def _print(node, depth):
        indent = "  " * depth
        if node["ph"] == "X":
            head = f"{node['name']} {node['dur_ms']:.3f} ms"
        else:
            head = f"* {node['name']}"
        extras = {k: v for k, v in node["args"].items()
                  if k not in ("trace_id", "trace_ids")}
        detail = (" " + " ".join(f"{k}={v}" for k, v
                                 in sorted(extras.items()))
                  if extras else "")
        print(f"{indent}{head} [{node['cat']}] "
              f"@{node['ts_ms']:.3f} ms (lane {node['tid']})"
              f"{detail}")
        for child in node["children"]:
            _print(child, depth + 1)

    for root in tree["tree"]:
        _print(root, 0)
    return 0


def run_diff(args) -> int:
    from pydcop_tpu.observability.trace import diff_trace_summaries

    events_a = _load(args.trace_a)
    if events_a is None:
        return 2
    events_b = _load(args.trace_b)
    if events_b is None:
        return 2
    rows = diff_trace_summaries(
        events_a, events_b, threshold=args.threshold,
        min_delta_ms=args.min_delta_ms,
    )
    regressions = [r for r in rows if r["regressed"]]
    if args.as_json:
        print(json.dumps({
            "a": args.trace_a, "b": args.trace_b,
            "threshold": args.threshold,
            "regressions": len(regressions),
            "rows": rows,
        }))
        return 1 if regressions else 0
    name_w = max([len(r["name"]) for r in rows] + [4])
    header = (f"{'name':<{name_w}}  {'count a>b':>11}  "
              f"{'total_ms a':>11}  {'total_ms b':>11}  "
              f"{'p50 a':>8}  {'p50 b':>8}  {'delta':>9}")
    print(f"{args.trace_a} -> {args.trace_b}")
    print(header)
    print("-" * len(header))
    for r in rows:
        flag = "  << REGRESSED" if r["regressed"] else ""
        print(
            f"{r['name']:<{name_w}}  "
            f"{r['count_a']:>5}>{r['count_b']:<5}  "
            f"{r['total_ms_a']:>11.3f}  {r['total_ms_b']:>11.3f}  "
            f"{r['p50_ms_a']:>8.3f}  {r['p50_ms_b']:>8.3f}  "
            f"{r['delta_total_ms']:>+9.3f}{flag}"
        )
    if regressions:
        print(f"{len(regressions)} span(s) regressed beyond "
              f"{args.threshold:.0%} (+{args.min_delta_ms} ms)")
        return 1
    return 0

"""Thread-mode solves for the full 14-algorithm surface.

VERDICT round-1 gap: dpop/mgm2/dba/gdba/syncbb/mixeddsa had no
agent-mode computations.  These tests run each through the real
threaded stack (orchestrator + agents + in-process transport,
reference run model) and check cost parity against the device path
where the algorithm is deterministic (dpop, syncbb) or solution
quality where it is stochastic.
"""

import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    constraint_from_str,
)
from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

from fixtures_paths import local

FIXTURE = local("coloring_chain.yaml")


def _dcop():
    return load_dcop_from_file(FIXTURE)


def _random_dcop(n=6, d=3, seed=5):
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("r", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        p = int(rng.integers(0, i))
        dcop.add_constraint(NAryMatrixRelation(
            [vs[p], vs[i]], rng.random((d, d)).round(2), f"c{i}"
        ))
    dcop.add_agents(
        [AgentDef(f"a{i}", capacity=100) for i in range(n)]
    )
    return dcop


class TestDpopAgentMode:
    def test_thread_solve_optimal(self):
        res = solve(_dcop(), "dpop", backend="thread", timeout=5)
        assert res["status"] == "FINISHED"
        assert res["cost"] == pytest.approx(-0.6)
        assert res["violations"] == 0

    def test_thread_matches_device(self):
        d = _random_dcop()
        r_thread = solve(d, "dpop", backend="thread", timeout=10)
        r_device = solve(d, "dpop", backend="device")
        assert r_thread["status"] == "FINISHED"
        assert r_thread["cost"] == pytest.approx(
            r_device["cost"], abs=1e-3
        )


class TestSyncBBAgentMode:
    def test_thread_solve_optimal(self):
        res = solve(_dcop(), "syncbb", backend="thread", timeout=5)
        assert res["status"] == "FINISHED"
        assert res["cost"] == pytest.approx(-0.6)
        assert res["violations"] == 0

    def test_thread_matches_device(self):
        d = _random_dcop(n=5, seed=9)
        r_thread = solve(d, "syncbb", backend="thread", timeout=10)
        r_device = solve(d, "syncbb", backend="device")
        assert r_thread["status"] == "FINISHED"
        assert r_thread["cost"] == pytest.approx(
            r_device["cost"], abs=1e-3
        )

    def test_max_mode(self):
        d = _random_dcop(n=4, seed=13)
        d._objective = "max"
        r_thread = solve(d, "syncbb", backend="thread", timeout=10)
        r_device = solve(d, "syncbb", backend="device")
        assert r_thread["cost"] == pytest.approx(
            r_device["cost"], abs=1e-3
        )


class TestMgm2AgentMode:
    def test_thread_solve(self):
        res = solve(
            _dcop(), "mgm2", backend="thread", timeout=10,
            algo_params={"stop_cycle": 30},
        )
        assert res["status"] == "FINISHED"
        assert res["violations"] == 0
        # 2-opt local search should reach one of the good minima of
        # this tiny fixture (-0.6 global, 0.0 1-opt traps).
        assert res["cost"] in (pytest.approx(-0.6), pytest.approx(0.0))

    def test_monotone_non_increasing(self):
        """MGM2's defining property: coordinated/unilateral moves never
        increase global cost across rounds."""
        d = _random_dcop(n=8, seed=21)
        costs = []

        def collector(metrics):
            if metrics.get("cost") is not None:
                costs.append(metrics["cost"])

        solve(
            d, "mgm2", backend="thread", timeout=15,
            algo_params={"stop_cycle": 15},
            collector=collector, collect_moment="cycle_change",
        )
        # Ignore the bootstrap (partial assignments while agents come
        # up, stretched further when the machine is loaded): monotone
        # over the last third of reports, plus overall descent from the
        # early phase — a fixed one-third cutoff flaked under load.
        assert len(costs) >= 3
        tail = costs[2 * len(costs) // 3:]
        for before, after in zip(tail, tail[1:]):
            assert after <= before + 1e-6
        assert costs[-1] <= costs[len(costs) // 3] + 1e-6


class TestDbaAgentMode:
    def _csp(self):
        # 3-coloring CSP: hard constraints only (cost >= infinity on
        # conflict), DBA's home turf.
        d = Domain("c", "", ["R", "G", "B"])
        dcop = DCOP("csp", objective="min")
        vs = [Variable(f"v{i}", d) for i in range(4)]
        for v in vs:
            dcop.add_variable(v)
        for i, j in [(0, 1), (1, 2), (2, 3), (3, 0), (0, 2)]:
            dcop.add_constraint(constraint_from_str(
                f"c{i}{j}",
                f"10000 if v{i} == v{j} else 0",
                [vs[i], vs[j]],
            ))
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=100) for i in range(4)]
        )
        return dcop

    def test_thread_solves_csp(self):
        res = solve(
            self._csp(), "dba", backend="thread", timeout=10,
            algo_params={"max_distance": 5},
        )
        assert res["status"] == "FINISHED"
        # DBA terminates via distance counters only when consistent.
        assert res["cost"] == 0
        assert res["violations"] == 0

    def test_stop_cycle_bound(self):
        res = solve(
            self._csp(), "dba", backend="thread", timeout=10,
            algo_params={"stop_cycle": 8, "max_distance": 1000},
        )
        assert res["status"] == "FINISHED"


class TestGdbaAgentMode:
    def test_thread_solve(self):
        res = solve(
            _dcop(), "gdba", backend="thread", timeout=10,
            algo_params={"stop_cycle": 20},
        )
        assert res["status"] == "FINISHED"
        assert res["violations"] == 0
        assert res["cost"] in (pytest.approx(-0.6), pytest.approx(0.0))

    @pytest.mark.parametrize("modifier,violation,increase", [
        ("M", "NM", "R"), ("A", "MX", "C"), ("A", "NZ", "T"),
    ])
    def test_modes_run(self, modifier, violation, increase):
        d = _random_dcop(n=5, seed=31)
        res = solve(
            d, "gdba", backend="thread", timeout=10,
            algo_params={
                "stop_cycle": 10, "modifier": modifier,
                "violation": violation, "increase_mode": increase,
            },
        )
        assert res["status"] == "FINISHED"
        assert len(res["assignment"]) == 5


class TestMixedDsaAgentMode:
    def _mixed(self):
        d = Domain("c", "", ["R", "G", "B"])
        dcop = DCOP("mixed", objective="min")
        vs = [Variable(f"v{i}", d) for i in range(4)]
        for v in vs:
            dcop.add_variable(v)
        # Hard ring + one soft preference.
        for i, j in [(0, 1), (1, 2), (2, 3)]:
            dcop.add_constraint(constraint_from_str(
                f"h{i}{j}",
                f"float('inf') if v{i} == v{j} else 0",
                [vs[i], vs[j]],
            ))
        dcop.add_constraint(constraint_from_str(
            "soft", "0 if v0 == v3 else 1", [vs[0], vs[3]],
        ))
        dcop.add_agents(
            [AgentDef(f"a{i}", capacity=100) for i in range(4)]
        )
        return dcop

    def test_thread_solves_hard_constraints(self):
        res = solve(
            self._mixed(), "mixeddsa", backend="thread", timeout=10,
            algo_params={"stop_cycle": 40, "proba_hard": 0.9},
        )
        assert res["status"] == "FINISHED"
        assert res["violations"] == 0

    def test_plain_coloring(self):
        res = solve(
            _dcop(), "mixeddsa", backend="thread", timeout=10,
            algo_params={"stop_cycle": 30},
        )
        assert res["status"] == "FINISHED"
        assert res["violations"] == 0


def test_all_14_algorithms_have_agent_computations():
    from pydcop_tpu.algorithms import list_available_algorithms
    from pydcop_tpu.infrastructure.agent_algorithms import (
        has_agent_computation,
    )

    algos = list_available_algorithms()
    assert len(algos) >= 14
    missing = [a for a in algos if not has_agent_computation(a)]
    assert missing == [], f"no agent computation for: {missing}"

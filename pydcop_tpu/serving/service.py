"""The multi-tenant solve service: queue, binning dispatch, results.

``SolveService`` turns the device engine into a throughput service:
callers :meth:`~SolveService.submit` DCOPs (each compiled on the
submitting thread — malformed problems fail synchronously, and
same-structure requests hit the PR-3 layout cache), a scheduler
thread (serving/scheduler.py) drains the bounded queue, bins requests
by structure signature (serving/binning.py) and dispatches each bin
as ONE vmapped device program (engine/batch.run_stacked, padded up
the bin-size ladder so ragged batch sizes reuse compiled programs).
Results stream back per request with latency accounting; admission
control (serving/admission.py) sheds load at the high-water mark and
opens a circuit breaker on repeated dispatch failure.

Request-plane telemetry (all registered on the process registry, so
the serving front end's ``/metrics`` exposes them):

- ``pydcop_requests_total{status}`` — every submit accounted:
  ``ok`` / ``error`` / ``rejected_queue_full`` /
  ``rejected_unavailable`` / ``rejected_bad_request``;
- ``pydcop_request_latency_seconds`` — submit→result histogram
  (p50/p99 straight off the buckets);
- ``pydcop_serve_queue_depth`` / ``pydcop_serve_batch_occupancy`` —
  live gauges;
- ``pydcop_serve_dispatches_total{kind}`` (``batched``/``solo``) and
  ``pydcop_serve_batched_requests_total`` — the batch-coalescing
  evidence (N same-structure requests in << N dispatches);
- per-batch ``serve_dispatch`` trace spans when tracing is on.
"""

import contextlib
import itertools
import logging
import queue
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine import batch as engine_batch
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.serving import binning
from pydcop_tpu.serving.admission import (
    AdmissionController,
    AdmissionPolicy,
    AdmissionRejected,
)

logger = logging.getLogger("pydcop.serving.service")

# Request terminal states.
QUEUED = "QUEUED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
ERROR = "ERROR"


@dataclass
class SolveRequest:
    """One in-flight problem: compiled form + bookkeeping."""

    id: str
    dcop: DCOP
    graph: Any
    meta: Any
    params: Dict[str, Any]
    bin: Any
    t_submit: float
    status: str = QUEUED
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[Dict[str, Any]] = None


class SolveService:
    """Bounded-queue, structure-binned batching solve service.

    Knobs: ``max_queue`` bounds the request queue (also the default
    admission high-water mark), ``batch_window_s`` is how long the
    scheduler lingers after the first request collecting batch-mates,
    ``max_batch`` caps one dispatch, ``bin_sizes`` is the
    padding ladder (engine/batch.DEFAULT_BIN_SIZES when None),
    ``default_params`` overrides the solver defaults
    (serving/binning.DEFAULT_PARAMS) service-wide, ``admission`` the
    backpressure/breaker policy, ``result_keep`` bounds completed-
    result retention (oldest evicted first — a long-lived service must
    not leak every response it ever produced).
    """

    def __init__(self, max_queue: int = 256,
                 batch_window_s: float = 0.02,
                 max_batch: int = 16,
                 bin_sizes: Optional[List[int]] = None,
                 default_params: Optional[Dict[str, Any]] = None,
                 admission: Optional[AdmissionPolicy] = None,
                 result_keep: int = 4096):
        if admission is None:
            admission = AdmissionPolicy(high_water=max_queue)
        self.admission = AdmissionController(admission)
        self.batch_window_s = batch_window_s
        self.max_batch = max(int(max_batch), 1)
        self.bin_sizes = tuple(
            bin_sizes or engine_batch.DEFAULT_BIN_SIZES)
        self.default_params = binning.normalize_params(default_params)
        self.result_keep = result_keep
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._requests: "OrderedDict[str, SolveRequest]" = OrderedDict()
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._scheduler = None
        self._started = False
        # Dispatch ledger (also mirrored into the registry).
        self.dispatches = 0
        self.batched_dispatches = 0
        self.completed = 0
        self.failed = 0
        reg = metrics_registry
        self._req_total = reg.counter(
            "pydcop_requests_total",
            "Solve-service requests by terminal status")
        self._latency = reg.histogram(
            "pydcop_request_latency_seconds",
            "Submit-to-result latency of solve-service requests")
        self._queue_depth = reg.gauge(
            "pydcop_serve_queue_depth",
            "Solve-service requests waiting in the queue")
        self._occupancy = reg.gauge(
            "pydcop_serve_batch_occupancy",
            "Real-instance fraction of the last dispatched batch")
        self._dispatch_total = reg.counter(
            "pydcop_serve_dispatches_total",
            "Device dispatches by kind (batched = >1 real instance)")
        self._batched_reqs = reg.counter(
            "pydcop_serve_batched_requests_total",
            "Requests that shared their device dispatch with others")
        self._pad_waste = reg.counter(
            "pydcop_serve_padded_lanes_total",
            "Padded (wasted) batch lanes dispatched to the device")

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> "SolveService":
        from pydcop_tpu.serving.scheduler import BinScheduler

        if self._started:
            return self
        # Activated like an ObservabilitySession: request-plane detail
        # counters should record while the service runs; the prior
        # state is restored on stop so an embedding process (tests,
        # bench) is left the way it was found.
        self._was_active = metrics_registry.active
        metrics_registry.active = True
        self._scheduler = BinScheduler(
            self, batch_window_s=self.batch_window_s,
            max_batch=self.max_batch)
        self._scheduler.start()
        self._started = True
        return self

    def stop(self, drain: bool = True,
             timeout: float = 30.0) -> None:
        """Stop the scheduler.  ``drain=True`` (default) lets queued
        requests finish first — a service shutdown must not silently
        drop accepted work; ``drain=False`` fails queued requests with
        a shutdown error instead."""
        if not self._started:
            return
        if drain:
            deadline = time.monotonic() + timeout
            while (not self._queue.empty()
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        self._scheduler.shutdown(timeout=timeout)
        self._scheduler = None
        self._started = False
        metrics_registry.active = self._was_active
        # Fail anything still queued (drain=False or drain timeout).
        # The queue may also hold the scheduler's unconsumed shutdown
        # sentinel — skip anything that isn't a request.
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if isinstance(req, SolveRequest):
                self._finish_error(req,
                                   "service stopped before dispatch")

    def __enter__(self) -> "SolveService":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- request plane ------------------------------------------------- #

    def submit(self, dcop: DCOP,
               params: Optional[Dict[str, Any]] = None,
               request_id: Optional[str] = None) -> str:
        """Admit, compile and enqueue one problem; returns the request
        id.  Raises :class:`~pydcop_tpu.serving.admission.
        AdmissionRejected` (429/503 at the front end) on backpressure
        and ``ValueError`` (400) on malformed problems/parameters.

        Compilation happens HERE, on the submitting thread: structure
        errors surface synchronously, concurrent clients compile in
        parallel, and the scheduler thread stays dedicated to device
        dispatch.  Same-structure submissions hit the PR-3 layout
        cache, so the steady-state compile cost is the cost-table
        fill."""
        if not self._started:
            raise RuntimeError("SolveService is not started")
        t_submit = time.perf_counter()
        try:
            self.admission.admit(self._queue.qsize())
        except AdmissionRejected as rejection:
            status = ("rejected_queue_full"
                      if rejection.http_status == 429
                      else "rejected_unavailable")
            self._req_total.inc(status=status)
            raise
        # Everything below is the caller's fault when it raises
        # (unknown/bad-typed params, malformed problem, duplicate id
        # -> 400 at the front end): still a ledger entry, so
        # pydcop_requests_total reconciles against client-side counts
        # even when clients misbehave.
        try:
            merged = dict(self.default_params)
            if params:
                merged.update(params)
            merged = binning.normalize_params(merged)
            graph, meta = compile_dcop(
                dcop, noise_level=merged["noise"])
            req = SolveRequest(
                id=request_id or f"r{next(self._ids)}",
                dcop=dcop, graph=graph, meta=meta, params=merged,
                bin=binning.bin_key(graph, merged),
                t_submit=t_submit,
            )
            with self._lock:
                if req.id in self._requests:
                    raise ValueError(
                        f"duplicate request id {req.id!r}")
                self._requests[req.id] = req
                self._prune_locked()
        except Exception:
            self._req_total.inc(status="rejected_bad_request")
            raise
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            # qsize raced past the high-water check: same contract as
            # an admission rejection, never a blocking put.
            with self._lock:
                self._requests.pop(req.id, None)
            self._req_total.inc(status="rejected_queue_full")
            raise QueueFullRace(
                f"request queue full ({self._queue.maxsize})")
        self._queue_depth.set(self._queue.qsize())
        return req.id

    def result(self, request_id: str,
               wait: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """The request's result dict, or None while pending.  With
        ``wait`` (seconds), block up to that long for completion.
        Raises ``KeyError`` for unknown ids."""
        with self._lock:
            req = self._requests.get(request_id)
        if req is None:
            raise KeyError(request_id)
        if wait:
            req.done.wait(wait)
        return req.result if req.done.is_set() else None

    def status(self, request_id: str) -> str:
        with self._lock:
            req = self._requests.get(request_id)
        if req is None:
            raise KeyError(request_id)
        return req.status

    def _prune_locked(self):
        """Evict oldest COMPLETED results past result_keep (pending
        requests are never evicted — their clients still hold the
        id).  Amortized O(excess), not a full-table scan: the table
        is insertion-ordered, so eviction pops completed entries off
        the front, rotating still-pending heads to the back (each
        entry rotates at most once per call, bounding the loop even
        when everything old is still pending)."""
        excess = len(self._requests) - self.result_keep
        if excess <= 0:
            return
        rotations = 0
        while excess > 0 and rotations < len(self._requests):
            rid = next(iter(self._requests))
            if self._requests[rid].done.is_set():
                del self._requests[rid]
                excess -= 1
            else:
                self._requests.move_to_end(rid)
                rotations += 1

    # -- dispatch plane (called by the scheduler thread) --------------- #

    def dispatch(self, reqs: List[SolveRequest]) -> None:
        """Solve one same-bin batch in a single device dispatch and
        complete every request in it.  Any engine failure fails the
        whole batch (each request gets the error) and feeds the
        breaker; success closes a half-open circuit."""
        for req in reqs:
            req.status = RUNNING
        self._queue_depth.set(self._queue.qsize())
        params = reqs[0].params
        span = (tracer.span(
            "serve_dispatch", "serving",
            bin=binning.bin_label(reqs[0].bin),
            n_real=len(reqs)) if tracer.enabled else None)
        try:
            with (span if span is not None
                  else contextlib.nullcontext()):
                values, cycles, batch_result = self._run_batch(
                    reqs, params)
                if span is not None:
                    span.args["batch_size"] = \
                        batch_result.metrics["batch_size"]
                    span.args["pad_fraction"] = \
                        batch_result.metrics["pad_fraction"]
        except Exception as exc:  # noqa: BLE001 — fail the batch, not
            # the scheduler thread: the service must keep serving.
            logger.warning("serve dispatch failed (%d requests): %s",
                           len(reqs), exc)
            self.admission.record_dispatch(ok=False)
            self._dispatch_total.inc(kind="failed")
            for req in reqs:
                self._finish_error(req, f"dispatch failed: {exc}")
            return
        self.admission.record_dispatch(ok=True)
        metrics = batch_result.metrics
        self.dispatches += 1
        kind = "batched" if len(reqs) > 1 else "solo"
        self._dispatch_total.inc(kind=kind)
        if len(reqs) > 1:
            self.batched_dispatches += 1
            self._batched_reqs.inc(len(reqs))
        self._occupancy.set(
            metrics["n_real"] / metrics["batch_size"])
        pad_lanes = metrics["batch_size"] - metrics["n_real"]
        if pad_lanes:
            self._pad_waste.inc(pad_lanes)
        t_done = time.perf_counter()
        for i, req in enumerate(reqs):
            # Per-request decode guard: one cost function that raises
            # on its own selected assignment must fail THAT request,
            # not the batch-mates (already solved) or the scheduler
            # thread (which serves everyone after them).
            try:
                assignment = req.meta.assignment_from_indices(
                    values[i])
                cost, violations = req.dcop.solution_cost(assignment)
            except Exception as exc:  # noqa: BLE001
                logger.warning("result decode failed for %s: %s",
                               req.id, exc)
                self._finish_error(req, f"result decode failed: {exc}")
                continue
            req.result = {
                "id": req.id,
                "status": FINISHED,
                "assignment": assignment,
                "cost": cost,
                "violations": violations,
                "cycles": int(cycles[i]),
                "latency": {
                    "total_s": t_done - req.t_submit,
                    "dispatch_s": batch_result.time_s,
                    "queued_s": (t_done - req.t_submit
                                 - batch_result.time_s),
                },
                "batch": {
                    "size": metrics["batch_size"],
                    "n_real": metrics["n_real"],
                    "pad_fraction": metrics["pad_fraction"],
                    "cold_start": metrics["cold_start"],
                },
            }
            req.status = FINISHED
            self.completed += 1
            self._req_total.inc(status="ok")
            self._latency.observe(t_done - req.t_submit)
            req.done.set()

    def _run_batch(self, reqs, params):
        """The device call, isolated for tests to stub failures."""
        return engine_batch.run_stacked(
            [r.graph for r in reqs],
            max_cycles=params["max_cycles"],
            damping=params["damping"],
            damping_nodes=params["damping_nodes"],
            stability=params["stability"],
            pad_to_bins=self.bin_sizes,
        )

    def _finish_error(self, req: SolveRequest, message: str):
        req.result = {
            "id": req.id, "status": ERROR, "error": message,
            "latency": {
                "total_s": time.perf_counter() - req.t_submit,
            },
        }
        req.status = ERROR
        self.failed += 1
        self._req_total.inc(status="error")
        req.done.set()

    # -- introspection ------------------------------------------------- #

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            tracked = len(self._requests)
        return {
            "queue_depth": self._queue.qsize(),
            "high_water": self.admission.policy.high_water,
            "breaker_state": self.admission.breaker_state,
            "dispatches": self.dispatches,
            "batched_dispatches": self.batched_dispatches,
            "completed": self.completed,
            "failed": self.failed,
            "tracked_requests": tracked,
            "max_batch": self.max_batch,
            "batch_window_s": self.batch_window_s,
            "bin_sizes": list(self.bin_sizes),
        }

    def health_summary(self) -> Dict[str, Any]:
        """The /healthz contribution: breaker open → failing (503)."""
        stats = self.stats()
        status = ("failing" if stats["breaker_state"] == "open"
                  else "ok")
        return {"status": status, "serving": stats}


class QueueFullRace(AdmissionRejected):
    """put_nowait lost the depth race: treated exactly like a
    high-water rejection (429)."""

    http_status = 429

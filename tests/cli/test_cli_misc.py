

class TestBackendGuard:
    """dcop_cli._guard_backend: probe-and-fallback only when a device
    command meets a configured accelerator plugin (a wedged tunnel
    hangs jax backend init forever — the guard is what keeps
    `pydcop solve` from hanging silently)."""

    def test_skips_without_plugin_env(self, monkeypatch):
        from pydcop_tpu import dcop_cli

        monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
        called = []
        monkeypatch.setattr(
            "pydcop_tpu.utils.cleanenv.ensure_live_backend",
            lambda **kw: called.append(kw))
        dcop_cli._guard_backend("solve")
        assert called == []

    def test_skips_non_device_commands(self, monkeypatch):
        from pydcop_tpu import dcop_cli

        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
        called = []
        monkeypatch.setattr(
            "pydcop_tpu.utils.cleanenv.ensure_live_backend",
            lambda **kw: called.append(kw))
        dcop_cli._guard_backend("graph")
        assert called == []

    def test_probes_device_commands_with_plugin(self, monkeypatch):
        from pydcop_tpu import dcop_cli
        from pydcop_tpu.utils import cleanenv

        monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
        monkeypatch.setenv("PYDCOP_CLI_PROBE_TIMEOUT", "7")
        called = []
        monkeypatch.setattr(
            cleanenv, "ensure_live_backend",
            lambda **kw: called.append(kw))
        dcop_cli._guard_backend("solve")
        assert called and called[0]["probe_timeout"] == 7.0
        assert called[0]["tag"] == "cli_solve"

"""SECP sharded scale acceptance (SURVEY §7.6 / BASELINE config #5):
a large smart-lighting-style factor population compiled, sharded over
the 8-device virtual mesh, solved, and per-device memory recorded.

The BASELINE config calls for 100k factors on a real v5e-8; on the
virtual CPU mesh we run a scaled-down (but structurally identical)
instance and assert the *sharding invariants* that make the 100k run
viable: row-count divisibility, per-device shard sizes ~1/8 of the
total, bit-identical results vs unsharded, and a recorded per-device
memory figure.
"""

import jax
import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.compile import compile_factor_graph
from pydcop_tpu.engine.runner import MaxSumEngine
from pydcop_tpu.engine.sharding import make_mesh, shard_graph

N_LIGHTS = 600
N_RULES = 8_000  # binary rule factors (light, light)
D = 5            # SECP light domain 0..4


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)
def test_secp_style_sharded_run_records_memory():
    rng = np.random.default_rng(0)
    dom = Domain("light", "light", list(range(D)))
    lights = [Variable(f"l{i}", dom) for i in range(N_LIGHTS)]
    # Rule factors: |li - target| + |lj - target| style tables.
    constraints = []
    for k in range(N_RULES):
        i, j = rng.choice(N_LIGHTS, size=2, replace=False)
        ti, tj = rng.integers(0, D, size=2)
        table = (
            np.abs(np.arange(D)[:, None] - ti)
            + np.abs(np.arange(D)[None, :] - tj)
        ).astype(np.float64)
        constraints.append(NAryMatrixRelation(
            [lights[i], lights[j]], table, f"r{k}"))

    mesh = make_mesh(8)
    graph8, meta = compile_factor_graph(
        lights, constraints, noise_level=0.01, noise_seed=0,
        pad_to=mesh.size,
    )
    # Sharding invariant: every bucket's row count divides the mesh.
    for b in graph8.buckets:
        assert b.costs.shape[0] % mesh.size == 0
    sharded = shard_graph(graph8, mesh)

    # Per-device memory accounting (SURVEY §7.6: "recording per-device
    # memory").  Bucket rows shard over the mesh; var tables replicate.
    bucket_bytes = sum(
        b.costs.nbytes + b.var_ids.nbytes for b in graph8.buckets
    )
    replicated_bytes = graph8.var_costs.nbytes + graph8.var_valid.nbytes
    per_device = bucket_bytes / mesh.size + replicated_bytes
    # Extrapolation sanity for the real 100k-factor v5e-8 target:
    # per-device HBM stays far under a v5e chip's 16 GB.
    scale_to_100k = 100_000 / N_RULES
    assert per_device * scale_to_100k < 16e9 * 0.05

    engine8 = MaxSumEngine(sharded, meta, mesh=mesh)
    res8 = engine8.run(max_cycles=30, stop_on_convergence=False)
    assert res8.cycles == 30

    # Near-parity vs unsharded on the identical compile.  NOT exact
    # equality: this seed's "bit-parity flake" (noted since PR 10) was
    # root-caused in PR 11 to a genuine f32 near-tie, not a sharding
    # bug — variable l410's two best beliefs differ by ONE ULP at
    # their magnitude (1.5e-05 at ~224.5, measured), so the sharded
    # halo psum's float reassociation legitimately flips that argmin
    # while every well-separated variable stays bit-identical.  The
    # assertion therefore allows disagreement only where the
    # assignments are cost-equivalent at f32 resolution: a handful of
    # flipped variables at most, and total costs equal to ~1e-5
    # relative (a REAL sharding bug would diverge the trajectories,
    # flipping many variables and moving the cost).  The strict
    # bit-parity discipline lives in tests/api/test_sharded_parity.py
    # on integer tables, where no ties exist to reassociate.
    graph1, meta1 = compile_factor_graph(
        lights, constraints, noise_level=0.01, noise_seed=0,
        pad_to=mesh.size,
    )
    res1 = MaxSumEngine(graph1, meta1).run(
        max_cycles=30, stop_on_convergence=False)
    differing = [
        name for name in res1.assignment
        if res1.assignment[name] != res8.assignment[name]
    ]
    assert len(differing) <= max(2, N_LIGHTS // 200), (
        f"{len(differing)} variables differ sharded-vs-not "
        f"({differing[:10]}...): beyond reassociation ties")

    # Solution quality: the run actually optimized (cost below a
    # random assignment's expected cost).
    def cost(asg):
        total = 0.0
        for c in constraints:
            v1, v2 = c.dimensions
            total += float(c(asg[v1.name], asg[v2.name]))
        return total

    # Cost-equivalence at f32 resolution: the flipped near-tie
    # variables (if any) must not move the solution quality.
    cost1, cost8 = cost(res1.assignment), cost(res8.assignment)
    assert abs(cost1 - cost8) <= 1e-4 * max(abs(cost1), 1.0), (
        f"sharded cost {cost8} vs unsharded {cost1}: beyond "
        "reassociation-tie tolerance")

    rand_cost = cost({
        v.name: int(rng.integers(0, D)) for v in lights
    })
    # Each light sits in ~27 rules with independently random targets,
    # so even the optimum pays ~2.2/factor vs ~3.2 for random — require
    # the solver to close most of that gap.
    assert cost(res8.assignment) < 0.78 * rand_cost